"""Benchmark sweep harness: the reference's §6 table grid, on TPU.

Reproduces the sweep the reference's authors ran by hand on the lab cluster
(BASELINE.md: 4 image sizes x {grey, rgb} x process counts, plus the CUDA
reps sweep) and the extra ``BASELINE.json`` configs (wider 5x5/7x7 halos,
8K x 1000-rep stress). Emits one markdown table (and optional CSV) with the
measured per-rep times, the achieved HBM bandwidth and % of v5e peak (the
honest roofline for a memory-bound stencil — a row far off the roofline is
a regression even when the speedup column looks good), and the speedup vs
the reference's published number where one exists.

Timing method: steady-state two-point differencing (autotune's
``_steady_state_per_rep``) — dispatch/fence overhead cancels, matching the
reference's compute-only MPI window semantics.

Usage:
    python -m tpu_stencil.runtime.bench_sweep [--quick] [--stress]
        [--csv out.csv] [--filters gaussian,gaussian5,gaussian7]
        [--backends xla,pallas]
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import List, Optional

import numpy as np

# Reference numbers (BASELINE.md). CUDA GTX-970 whole-program seconds at the
# matching reps column; MPI n=1 compute-only seconds (20 reps assumed).
_CUDA_40REPS = {
    ("grey", 630): 0.076, ("grey", 1260): 0.116,
    ("grey", 2520): 0.172, ("grey", 5040): 0.189,
    ("rgb", 630): 0.307, ("rgb", 1260): 0.537,
    ("rgb", 2520): 1.017, ("rgb", 5040): 1.837,
}

SIZES = (630, 1260, 2520, 5040)
WIDTH = 1920


def _measure_per_rep(
    img: np.ndarray, filter_name: str, budget_s: float, backend: str
):
    """Steady-state seconds/rep; N scaled so each measurement runs
    ~budget_s on device. Returns ``(per_rep_s, resolved_backend,
    schedule, block_h, fuse)`` — for explicit backends the last three
    are None/None/None; ``auto``/``autotune`` rows resolve through the
    model (the DEFAULT path: tuned backend, schedule, and geometry per
    shape, disk-cached) and the sweep then times exactly that resolved
    configuration, so an auto row is what a bare-CLI user measures."""
    import jax
    import jax.numpy as jnp

    from tpu_stencil.models.blur import IteratedConv2D, iterate
    from tpu_stencil.runtime.autotune import _steady_state_per_rep

    model = IteratedConv2D(filter_name, backend=backend)
    shape2 = tuple(img.shape[:2])
    ch = img.shape[2] if img.ndim == 3 else 1
    if backend in ("auto", "autotune"):
        resolved, sched = model.resolved_config(shape2, ch)
        bh, fz = model.resolved_geometry(shape2, ch)
    else:
        resolved, sched, bh, fz = backend, None, None, None

    def timed(n_reps: int) -> float:
        dev = jax.device_put(img)
        np.asarray(dev.ravel()[0])
        t0 = time.perf_counter()
        out = iterate(dev, jnp.int32(n_reps), plan=model.plan,
                      backend=resolved, schedule=sched, block_h=bh, fuse=fz)
        np.asarray(out.ravel()[0])
        return time.perf_counter() - t0

    timed(1)  # compile fence
    probe_reps = 500
    est = max(timed(probe_reps) / probe_reps, 1e-8)
    lo = min(max(int(budget_s / est), 200), 50_000)
    return _steady_state_per_rep(timed, lo), resolved, sched, bh, fz


def _measure_batch_per_frame_rep(
    imgs: np.ndarray, filter_name: str, budget_s: float,
    backend: str = "xla",
):
    """Steady-state seconds per frame-repetition of the batch mode
    (``--frames``): frames are embarrassingly parallel, so the interesting
    number is us per frame*rep vs the single-frame row. ``backend='xla'``
    measures the vmapped step; ``'pallas'`` the fused tall-image kernel
    (``pallas_stencil.iterate_frames``); ``'auto'``/``'autotune'``
    resolve through the model's batch path (tuned backend, schedule, and
    geometry) and measure exactly that. Returns ``(per_frame_rep_s,
    resolved_backend, schedule, block_h, fuse)``."""
    import functools

    import jax
    import jax.numpy as jnp

    from tpu_stencil.models.blur import IteratedConv2D, iterate_batch
    from tpu_stencil.runtime.autotune import _steady_state_per_rep

    model = IteratedConv2D(filter_name, backend=backend)
    frame_shape = tuple(imgs.shape[1:3])
    ch = imgs.shape[3] if imgs.ndim == 4 else 1
    resolved, sched, bh, fz = backend, None, None, None
    if backend in ("auto", "autotune"):
        resolved, sched = model.batch_config(
            frame_shape, ch, True, n_frames=imgs.shape[0]
        )
        bh, fz = model.resolved_geometry(frame_shape, ch)
    if resolved == "pallas":
        from tpu_stencil.ops import pallas_stencil

        # Mosaic compiles for TPU only; interpret is acceptable on CPU
        # (where everything is slow anyway) but on any other platform a
        # silently-interpreted run would be reported as a 'pallas' row —
        # fail loudly instead (same guard as blur._iterate_impl).
        plat = jax.default_backend()
        if plat not in ("tpu", "cpu"):
            raise NotImplementedError(
                "the Pallas frames benchmark targets TPU (interpret mode "
                f"on CPU); on {plat!r} sweep with --backends xla"
            )
        fn = jax.jit(
            functools.partial(
                pallas_stencil.iterate_frames, plan=model.plan,
                interpret=plat == "cpu", schedule=sched,
                block_h=bh, fuse=fz,
            ),
            donate_argnums=0,
        )
    else:
        fn = functools.partial(
            iterate_batch, plan=model.plan, backend=resolved
        )

    def timed(n_reps: int) -> float:
        dev = jax.device_put(imgs)
        np.asarray(dev.ravel()[0])
        t0 = time.perf_counter()
        out = fn(dev, jnp.int32(n_reps))
        np.asarray(out.ravel()[0])
        return time.perf_counter() - t0

    timed(1)
    probe = 100
    est = max(timed(probe) / probe, 1e-8)
    lo = min(max(int(budget_s / est), 100), 50_000)
    per = _steady_state_per_rep(timed, lo) / imgs.shape[0]
    return per, resolved, sched, bh, fz


def _pallas_label(filter_name: str, frame_h: int,
                  n_frames: int = 1) -> str:
    """Row label recording which per-rep schedule actually produced a
    pallas measurement: the kernel default (TPU_STENCIL_PALLAS_SCHEDULE)
    after any degrade at this launch's block height — the artifact must
    never attribute a degraded run to the schedule that could not apply.
    ``n_frames > 1`` labels the fused tall-image batch launch."""
    from tpu_stencil.models.blur import IteratedConv2D
    from tpu_stencil.ops import pallas_stencil as ps

    plan = IteratedConv2D(filter_name).plan
    rows = (
        frame_h if n_frames == 1  # single-frame launch: no gap rows
        else n_frames * ps.frames_stride(plan, frame_h)
    )
    ran = ps.effective_schedule_for(plan, rows)
    return f"pallas[{ran}]"


def _with_retries(measure_fn, label: str, retries: int = 2):
    """Run one measurement under the shared retry policy
    (:mod:`tpu_stencil.resilience.retry`): transient tunnel drops must
    not kill a (possibly hours-long) sweep, while deterministic
    failures — capability guards (NotImplementedError), shape/validation
    errors — can never succeed on retry and fail fast instead of burning
    the backoff budget. The classifier is the same one serve and stream
    use, so "what bench retries" can never drift from "what the engines
    retry"."""
    from tpu_stencil.resilience import retry as _retry

    def on_retry(attempt, e):
        print(f"row {label} attempt {attempt} failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr, flush=True)

    return _retry.retry_call(
        measure_fn,
        policy=_retry.RetryPolicy(attempts=retries + 1, base_delay=15.0,
                                  multiplier=2.0, max_delay=120.0),
        on_retry=on_retry,
        label=f"bench_sweep[{label}]",
    )


def _row(img, filter_name, mode, size_label, backend, budget_s, reps,
         base, retries: int = 2) -> dict:
    from tpu_stencil.runtime import roofline

    per_rep, resolved, sched, bh, fz = _with_retries(
        lambda: _measure_per_rep(img, filter_name, budget_s, backend),
        f"{size_label} [{backend}]", retries,
    )
    total = per_rep * reps
    # Roofline at the RESOLVED backend AND geometry: the traffic model
    # (fused vs per-rep HBM, fuse depth) follows what actually ran.
    gbps, pct = roofline.achieved(
        img.nbytes, per_rep, resolved, filter_name, img.shape[0],
        block_h=bh, fuse=fz,
    )
    if backend in ("auto", "autotune"):
        label = f"auto:{resolved}"
        if resolved == "pallas":
            label = f"auto:pallas[{sched}]"
            if bh is not None or fz is not None:
                label += f"@{bh}x{fz}"
    elif backend == "pallas":
        label = _pallas_label(filter_name, img.shape[0])
    else:
        label = backend
    return {
        "filter": filter_name, "mode": mode, "size": size_label,
        "backend": label,
        "us_per_rep": round(per_rep * 1e6, 1),
        "reps": reps,
        "total_s": round(total, 6),
        "hbm_gbps": round(gbps, 1),
        "pct_hbm_peak": round(pct, 1),
        "gtx970_40reps_s": base,
        "speedup_vs_gtx970": round(base / total, 1) if base else None,
    }


def _measure_pipe_per_frame_rep(
    img: np.ndarray, filter_name: str, stages: int, budget_s: float,
):
    """Steady-state seconds per frame-repetition through a K-stage
    temporal pipeline (docs/STREAMING.md "Temporal pipeline"): the rep
    loop split over K mesh slices, the same frame fed every tick, each
    steady-state tick completing one fully-processed frame. The fill
    ticks run before the timer starts, so the number is the systolic
    steady state — comparable to the batch row (both are us per
    frame*rep), not to the single-frame latency rows."""
    import jax

    from tpu_stencil.models.blur import IteratedConv2D
    from tpu_stencil.parallel.pipeline import PipelineRunner

    model = IteratedConv2D(filter_name, backend="xla")
    channels = img.shape[2] if img.ndim == 3 else 1
    runner = PipelineRunner(
        model, tuple(img.shape[:2]), channels, stages,
        devices=jax.devices()[:stages],
    )
    tile = np.zeros(runner.local_shape, np.uint8)
    tile[0, : img.shape[0], : img.shape[1]] = img
    d0 = runner.stage0_devices[0]
    inp = runner.assemble_input({d0.id: jax.device_put(tile, d0)})
    reps = 40
    carry = runner.warm(reps)
    for _ in range(stages):  # fill: every stage holds a frame
        carry, out = runner.tick(carry, inp, reps)
    jax.block_until_ready(out)
    n = 0
    t0 = time.perf_counter()
    while True:
        carry, out = runner.tick(carry, inp, reps)
        jax.block_until_ready(out)
        n += 1
        if n >= 3 and time.perf_counter() - t0 > budget_s:
            break
    return (time.perf_counter() - t0) / n / reps


def run_sweep(
    quick: bool = False,
    stress: bool = False,
    filters: Optional[List[str]] = None,
    csv_path: Optional[str] = None,
    backends: Optional[List[str]] = None,
    frames: int = 0,
    pipe_stages: int = 1,
) -> List[dict]:
    filters = filters or ["gaussian"]
    backends = backends or ["xla"]
    rng = np.random.default_rng(0)
    budget_s = 0.1 if quick else 0.5
    rows = []
    writer = _IncrementalCsv(csv_path)  # survives a tunnel drop mid-sweep
    sizes = SIZES[:2] if quick else SIZES

    def add(row):
        rows.append(row)
        writer.write(row)
        print(_fmt_row(row), file=sys.stderr, flush=True)

    for backend in backends:
        for filter_name in filters:
            for mode in ("grey", "rgb"):
                for h in sizes:
                    shape = (h, WIDTH) if mode == "grey" else (h, WIDTH, 3)
                    img = rng.integers(0, 256, size=shape, dtype=np.uint8)
                    base = (
                        _CUDA_40REPS.get((mode, h))
                        if filter_name == "gaussian" else None
                    )
                    add(_row(img, filter_name, mode, f"{WIDTH}x{h}",
                             backend, budget_s, 40, base))
        if stress:
            img = rng.integers(0, 256, size=(4320, 7680, 3), dtype=np.uint8)
            add(_row(img, "gaussian", "rgb", "7680x4320 (8K)",
                     backend, budget_s * 4, 1000, None))
    if frames:
        imgs = rng.integers(
            0, 256, size=(frames, 2520, WIDTH, 3), dtype=np.uint8
        )
        from tpu_stencil.runtime import roofline

        for backend in backends:
            per_fr, resolved, sched, bh, fz = _with_retries(
                lambda: _measure_batch_per_frame_rep(
                    imgs, "gaussian", budget_s, backend
                ),
                f"x{frames} frames [{backend}]",
            )
            gbps, pct = roofline.achieved(
                imgs.nbytes // frames, per_fr, resolved, "gaussian", 2520,
                block_h=bh, fuse=fz,
            )
            if backend in ("auto", "autotune"):
                label = f"auto:{resolved}"
                if resolved == "pallas":
                    label = f"auto:pallas[{sched}]"
                    if bh is not None or fz is not None:
                        label += f"@{bh}x{fz}"
            elif backend == "pallas":
                label = _pallas_label("gaussian", 2520, n_frames=frames)
            else:
                label = backend
            add({
                "filter": "gaussian", "mode": "rgb",
                "size": f"{WIDTH}x2520 x{frames} frames", "backend": label,
                "us_per_rep": round(per_fr * 1e6, 1), "reps": 40,
                "total_s": round(per_fr * 40 * frames, 6),
                "hbm_gbps": round(gbps, 1), "pct_hbm_peak": round(pct, 1),
                "gtx970_40reps_s": _CUDA_40REPS[("rgb", 2520)] * frames,
                "speedup_vs_gtx970": round(
                    _CUDA_40REPS[("rgb", 2520)] / (per_fr * 40), 1
                ),
            })
    if pipe_stages > 1:
        import jax

        from tpu_stencil.runtime import roofline

        if len(jax.devices()) < pipe_stages:
            print(
                f"pipe row skipped: {pipe_stages} stages need "
                f"{pipe_stages} devices, have {len(jax.devices())}",
                file=sys.stderr, flush=True,
            )
        else:
            img = rng.integers(0, 256, size=(2520, WIDTH, 3), dtype=np.uint8)
            per_fr = _with_retries(
                lambda: _measure_pipe_per_frame_rep(
                    img, "gaussian", pipe_stages, budget_s
                ),
                f"pipe{pipe_stages} [xla]",
            )
            gbps, pct = roofline.achieved(
                img.nbytes, per_fr, "xla", "gaussian", 2520
            )
            add({
                "filter": "gaussian", "mode": "rgb",
                "size": f"{WIDTH}x2520 pipe{pipe_stages}",
                "backend": f"xla:pipe{pipe_stages}",
                "us_per_rep": round(per_fr * 1e6, 1), "reps": 40,
                "total_s": round(per_fr * 40, 6),
                "hbm_gbps": round(gbps, 1), "pct_hbm_peak": round(pct, 1),
                "gtx970_40reps_s": _CUDA_40REPS[("rgb", 2520)],
                "speedup_vs_gtx970": round(
                    _CUDA_40REPS[("rgb", 2520)] / (per_fr * 40), 1
                ),
            })
    return rows


class _IncrementalCsv:
    """Append each row as it is measured; a crash loses nothing."""

    def __init__(self, path: Optional[str]) -> None:
        self.path = path
        self._writer = None
        self._file = None

    def write(self, row: dict) -> None:
        if not self.path:
            return
        import csv

        if self._writer is None:
            self._file = open(self.path, "w", newline="")
            self._writer = csv.DictWriter(self._file, fieldnames=list(row.keys()))
            self._writer.writeheader()
        self._writer.writerow(row)
        self._file.flush()


def _fmt_row(r: dict) -> str:
    sp = f"{r['speedup_vs_gtx970']}x" if r["speedup_vs_gtx970"] else "-"
    return (f"{r['filter']:>10} {r['mode']:>4} {r['size']:>12} "
            f"[{r['backend']}]: {r['us_per_rep']:>8} us/rep, "
            f"{r['hbm_gbps']:>6} GB/s ({r['pct_hbm_peak']}% peak), "
            f"{r['reps']} reps = {r['total_s']:.4f} s, vs GTX-970 {sp}")


def emit_markdown(rows: List[dict]) -> str:
    lines = [
        "| filter | mode | size | backend | us/rep | HBM GB/s | % peak "
        "| reps | total (s) | GTX-970 40 reps (s) | speedup |",
        "|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['filter']} | {r['mode']} | {r['size']} | {r['backend']} "
            f"| {r['us_per_rep']} | {r['hbm_gbps']} | {r['pct_hbm_peak']} "
            f"| {r['reps']} | {r['total_s']} | {r['gtx970_40reps_s'] or '-'} "
            f"| {str(r['speedup_vs_gtx970']) + 'x' if r['speedup_vs_gtx970'] else '-'} |"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--quick", action="store_true", help="2 sizes, short runs")
    p.add_argument("--stress", action="store_true", help="add the 8K x1000 config")
    p.add_argument("--csv", default=None, help="also write CSV here")
    p.add_argument(
        "--filters", default="gaussian",
        help="comma-separated filter names (default gaussian)",
    )
    p.add_argument(
        "--backends", default="xla",
        help="comma-separated backends to sweep (xla,pallas)",
    )
    p.add_argument(
        "--frames", type=int, default=0, metavar="N",
        help="also measure the batch mode with N north-star frames, one "
             "row per swept backend (xla = vmapped step, pallas = fused "
             "tall-image kernel); reports us per frame*rep",
    )
    p.add_argument(
        "--pipe-stages", type=int, metavar="K",
        default=int(os.environ.get("TPU_STENCIL_BENCH_PIPE") or 1),
        help="also measure the K-stage temporal pipeline at the "
             "north-star size (us per frame*rep, steady state; needs K "
             "devices); defaults to TPU_STENCIL_BENCH_PIPE so a sentry "
             "burst turns the row on with the same knob bench.py uses",
    )
    p.add_argument(
        "--platform", default=None, choices=["cpu", "tpu", "gpu"],
        help="force the JAX platform via the config API (same contract as "
             "the CLI flag — wins over a pinned JAX_PLATFORMS); rehearsal "
             "use, real sweeps run on the default TPU",
    )
    ns = p.parse_args(argv)
    if ns.platform:
        import jax

        jax.config.update("jax_platforms", ns.platform)
    rows = run_sweep(
        quick=ns.quick, stress=ns.stress,
        filters=ns.filters.split(","), csv_path=ns.csv,
        backends=ns.backends.split(","), frames=ns.frames,
        pipe_stages=ns.pipe_stages,
    )
    print(emit_markdown(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
