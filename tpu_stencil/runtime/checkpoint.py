"""Checkpoint / resume for long iterated-stencil runs.

The reference has no checkpointing (SURVEY.md §5 — intermediate repetitions
live only in its double buffers and a crash at rep 999/1000 loses
everything). Here the iteration state is just the current uint8 frame, so a
checkpoint is: the frame's raw bytes plus a JSON sidecar recording how many
repetitions it already contains and the config fingerprint. Writes are
atomic (tmp + rename), restores validate the fingerprint so a checkpoint
from a different image/filter/size is refused rather than silently resumed.

Enabled from the CLI via ``--checkpoint-every N`` / ``--resume``; the driver
splits the rep loop into N-rep chunks (still fully on-device — the chunking
only adds one host sync per N reps).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

import numpy as np

from tpu_stencil.config import JobConfig
from tpu_stencil.integrity import checksum as _checksum
from tpu_stencil.io import native
from tpu_stencil.io.raw import fsync_path


class CorruptCheckpoint(ValueError):
    """A checkpoint sidecar failed its embedded CRC (or no longer
    parses): a flipped bit in durable state. Refuse-to-resume, typed,
    NAMING the file — the operator deletes (or restores) that one
    artifact instead of debugging why a resumed run diverged. A
    ``ValueError`` so every resume path classifies it permanent."""

    def __init__(self, path: str, why: str) -> None:
        super().__init__(
            f"checkpoint sidecar {path} is corrupt ({why}); refusing "
            f"to resume from it — delete the file to start over, or "
            f"restore it from a good copy"
        )
        self.path = path


def _canonical_body(meta: dict) -> bytes:
    """The bytes the sidecar CRC covers: canonical JSON of every field
    except the stamp itself. ONE serialization shared by writer and
    verifier — a drifting copy would reject every fresh sidecar."""
    return json.dumps(
        {k: meta[k] for k in sorted(meta) if k != "crc32c"},
        sort_keys=True,
    ).encode()


def _stamp_crc(meta: dict) -> dict:
    """``meta`` with its embedded integrity CRC: crc32c over the
    canonical JSON of every OTHER field. A sidecar that parses but was
    bit-flipped (a digit changed inside ``frames_done``) is exactly the
    corruption JSON cannot see and this stamp can."""
    return dict(meta, crc32c=_checksum.crc32c(_canonical_body(meta)))


def _load_meta(path: str) -> dict:
    """Parse + integrity-check a sidecar. Unparseable JSON or a CRC
    mismatch raises :class:`CorruptCheckpoint` naming the file;
    sidecars written before the CRC existed (no ``crc32c`` key) load
    unchecked — fingerprint validation still applies to them."""
    with open(path) as f:
        raw = f.read()
    try:
        meta = json.loads(raw)
    except ValueError as e:
        raise CorruptCheckpoint(path, f"unparseable JSON: {e}") from None
    if not isinstance(meta, dict):
        raise CorruptCheckpoint(
            path, f"top-level {type(meta).__name__}, expected object"
        )
    if "crc32c" in meta:
        got = _checksum.crc32c(_canonical_body(meta))
        if got != meta["crc32c"]:
            raise CorruptCheckpoint(
                path, f"embedded crc32c {meta['crc32c']} != computed {got}"
            )
    return meta


def _write_meta(path: str, meta: dict) -> None:
    """The one sidecar commit path: CRC-stamped, fsynced, atomically
    renamed — torn on no axis (parse, content, publication)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(_stamp_crc(meta), f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _paths(cfg: JobConfig) -> Tuple[str, str]:
    base = cfg.output_path + ".ckpt"
    return base, base + ".json"


def _fingerprint(cfg: JobConfig) -> dict:
    return {
        "image": os.path.abspath(cfg.image),
        "width": cfg.width,
        "height": cfg.height,
        "channels": cfg.channels,
        "filter": cfg.filter_name,
        "repetitions": cfg.repetitions,
        "frames": cfg.frames,
        # Boundary semantics change every pixel near an edge: resuming a
        # zero-boundary checkpoint under periodic (or vice versa) would
        # mix semantics silently.
        "boundary": cfg.boundary,
    }


def _check_meta(meta: dict, cfg: JobConfig, where: str) -> None:
    """Refuse a checkpoint written for a different job. Pre-boundary
    checkpoints lack the key; they were all written under zero-boundary
    semantics (the only mode that existed)."""
    want = _fingerprint(cfg)
    if {k: meta.get(k, "zero" if k == "boundary" else None)
            for k in want} != want:
        raise ValueError(
            f"checkpoint at {where} was written for a different job "
            f"({meta} != {want}); delete it or change --output"
        )


def _commit_meta(cfg: JobConfig, rep: int, versioned: str) -> None:
    """Sharded-format commit: after a cross-host barrier (every writer's
    data is durable), process 0 atomically publishes the metadata naming
    the versioned data file, then sweeps older versions."""
    import jax

    data_path, meta_path = _paths(cfg)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt_data_r{rep}")
    if jax.process_index() == 0:
        meta = dict(_fingerprint(cfg), rep=rep,
                    data=os.path.basename(versioned))
        _write_meta(meta_path, meta)
        for name in _stale_versions(data_path, before_rep=rep):
            os.remove(name)


def _checkpoint_fault(index: int) -> None:
    """The ``checkpoint`` injection point (resilience.faults): checked
    at every checkpoint commit so chaos tests exercise the
    crash-mid-save path the atomic tmp-then-rename discipline exists
    for. site() resolution is per commit, not per rep — checkpoints are
    already host-sync points, never the hot path."""
    from tpu_stencil.resilience import faults as _faults

    site = _faults.site("checkpoint")
    if site is not None:
        site(index)


def save(cfg: JobConfig, rep: int, frame: np.ndarray) -> None:
    """Atomically persist the frame as the state after ``rep`` repetitions."""
    _checkpoint_fault(rep)
    data_path, meta_path = _paths(cfg)
    tmp = data_path + ".tmp"
    arr = np.ascontiguousarray(np.asarray(frame, np.uint8))
    native.pwrite_full(tmp, 0, arr.tobytes(), truncate=True)
    fsync_path(tmp)  # the data must be stable before its name is
    os.replace(tmp, data_path)
    _write_meta(meta_path, dict(_fingerprint(cfg), rep=rep))


def restore(cfg: JobConfig) -> Optional[Tuple[int, np.ndarray]]:
    """Return (completed reps, frame) from a matching checkpoint, or None."""
    data_path, meta_path = _paths(cfg)
    if not os.path.exists(meta_path):
        return None
    meta = _load_meta(meta_path)
    _check_meta(meta, cfg, data_path)
    path = data_path
    if meta.get("data"):  # sharded-format checkpoint: versioned data file
        path = os.path.join(os.path.dirname(data_path) or ".", meta["data"])
    if not os.path.exists(path):
        return None
    buf = native.pread_full(path, 0, cfg.nbytes)
    shape = (
        (cfg.height, cfg.width)
        if cfg.channels == 1
        else (cfg.height, cfg.width, cfg.channels)
    )
    if cfg.frames > 1:
        shape = (cfg.frames,) + shape
    frame = np.frombuffer(buf, np.uint8).reshape(shape)
    return int(meta["rep"]), frame


def save_sharded(cfg: JobConfig, rep: int, out_dev) -> None:
    """Multi-host checkpoint: every process writes its addressable shards
    into one shared data file (the ``write_sharded`` MPI-IO pattern), then —
    after a cross-host barrier — process 0 commits the metadata.

    Data files are versioned per rep (``<base>.ckpt.r<rep>``) so an
    in-flight write can never corrupt the last committed checkpoint: the
    metadata names the data file it refers to and is only replaced once the
    data is complete on every host. Requires a shared filesystem, the same
    assumption the reference's MPI-IO made (SURVEY.md §2 C6/C16).
    """
    from tpu_stencil.parallel import distributed

    data_path, _ = _paths(cfg)
    versioned = f"{data_path}.r{rep}"
    distributed.write_sharded(
        versioned, out_dev, cfg.height, cfg.width, cfg.channels
    )
    _commit_meta(cfg, rep, versioned)


def save_frames_sharded(
    cfg: JobConfig, rep: int, frames_local, f0: int
) -> None:
    """Multi-host ``--frames`` checkpoint: every process pwrites its
    contiguous frame range [f0, f0 + n) into one shared versioned data
    file (the clip's own byte layout), then — after the cross-host
    barrier — process 0 commits the metadata. Frame-less processes pass
    ``frames_local=None``: they write nothing but MUST still call this
    every chunk (the commit barrier counts every process)."""
    data_path, _ = _paths(cfg)
    versioned = f"{data_path}.r{rep}"
    frame_bytes = cfg.height * cfg.width * cfg.channels
    if frames_local is not None and len(frames_local):
        arr = np.ascontiguousarray(np.asarray(frames_local, np.uint8))
        native.ensure_size(versioned, cfg.frames * frame_bytes)
        native.pwrite_full(versioned, f0 * frame_bytes, arr.tobytes())
    _commit_meta(cfg, rep, versioned)


def restore_frames_sharded(
    cfg: JobConfig, f0: int, n_local: int
) -> Optional[Tuple[int, np.ndarray]]:
    """Return (completed reps, this host's frames [f0, f0 + n_local))
    from a matching checkpoint, or None. Sharded-format data is read by
    byte range (each host touches only its own frames); a legacy
    single-host whole-clip checkpoint is read whole and sliced, so
    progress survives a switch to multi-host."""
    data_path, meta_path = _paths(cfg)
    if not os.path.exists(meta_path):
        return None
    meta = _load_meta(meta_path)
    _check_meta(meta, cfg, meta_path)
    frame_bytes = cfg.height * cfg.width * cfg.channels
    if meta.get("data"):
        versioned = os.path.join(
            os.path.dirname(data_path) or ".", meta["data"]
        )
        if not os.path.exists(versioned):
            return None
        buf = native.pread_full(
            versioned, f0 * frame_bytes, n_local * frame_bytes
        )
        shape = (n_local, cfg.height, cfg.width)
        if cfg.channels != 1:
            shape += (cfg.channels,)
        return int(meta["rep"]), np.frombuffer(buf, np.uint8).reshape(shape)
    legacy = restore(cfg)
    if legacy is None:
        return None
    rep, clip = legacy
    return rep, clip[f0:f0 + n_local]


def restore_sharded(cfg: JobConfig, sharding) -> Optional[Tuple[int, "object"]]:
    """Return (completed reps, global sharded array) from a matching
    checkpoint, or None. Sharded-format checkpoints are read per-process
    (each host touches only its shards' row ranges); single-host-format
    checkpoints (written by non-mesh runs, or by older versions) are read
    whole on every host and resharded — progress is never silently
    discarded across formats."""
    import jax

    from tpu_stencil.parallel import distributed

    data_path, meta_path = _paths(cfg)
    if not os.path.exists(meta_path):
        return None
    meta = _load_meta(meta_path)
    _check_meta(meta, cfg, meta_path)
    if meta.get("data"):
        versioned = os.path.join(
            os.path.dirname(data_path) or ".", meta["data"]
        )
        if not os.path.exists(versioned):
            return None
        arr = distributed.read_sharded(
            versioned, cfg.height, cfg.width, cfg.channels, sharding
        )
        return int(meta["rep"]), arr
    # Legacy single-host format: every host reads the full frame (shared
    # filesystem) and reshards it to the requested layout.
    legacy = restore(cfg)
    if legacy is None:
        return None
    rep, frame = legacy
    if frame.ndim == 2:
        frame = frame[..., None]
    from tpu_stencil.parallel.mesh import COLS_AXIS, ROWS_AXIS

    r = sharding.mesh.shape[ROWS_AXIS]
    c = sharding.mesh.shape[COLS_AXIS]
    padded_h = -(-cfg.height // r) * r
    padded_w = -(-cfg.width // c) * c
    padded = np.zeros((padded_h, padded_w, cfg.channels), np.uint8)
    padded[: cfg.height, : cfg.width] = frame
    if cfg.channels == 1:
        padded = padded[..., 0]
    arr = jax.make_array_from_callback(
        padded.shape, sharding, lambda idx: padded[idx]
    )
    return rep, arr


class MeshCursorMismatch(ValueError):
    """A ``--resume`` of a mesh-composed stream run under a different
    mesh topology than the one that wrote the checkpoint — the fan
    width (``--mesh-frames`` device count), the spatial shard topology
    (``--shard-frames RxC``), or the temporal stage count
    (``--pipe-stages K``). The recorded cursor/scatter/fill layout is
    aligned to the writing run's topology, so silently adopting it
    under another one would misattribute frames to devices (fan),
    mis-scatter tiles (shard) or mis-weave the deal (pipeline); the
    resume must fail typed, naming both topologies (the recorded one
    and the requested one).

    ``recorded``/``requested`` are device counts (ints) for the fan
    guard, descriptive topology strings for the spatial-shard and
    pipeline guards."""

    def __init__(self, recorded, requested, path: str) -> None:
        if isinstance(recorded, str) or isinstance(requested, str):
            super().__init__(
                f"stream checkpoint at {path} records topology "
                f"{recorded} but --resume is running {requested}; "
                f"re-run at the recorded topology (or delete the "
                f"checkpoint to start over)"
            )
        else:
            super().__init__(
                f"stream checkpoint at {path} was written by a "
                f"{recorded}-device mesh-fan run but --resume is running "
                f"on {requested} device(s); re-run with --mesh-frames "
                f"{recorded} (or delete the checkpoint to start over)"
            )
        self.recorded = recorded
        self.requested = requested


def _stream_paths(cfg) -> str:
    """The stream progress sidecar lives beside the sink (the artifact
    it describes), like the frame checkpoints beside the job output.
    Normalized: ``outdir`` and ``outdir/`` are the same sink and must
    resolve to the same sidecar, or a resume spelled the other way
    silently finds no checkpoint."""
    return cfg.output_path.rstrip(os.sep) + ".stream.ckpt.json"


def _stream_fingerprint(cfg) -> dict:
    """Identity of a streaming job (:class:`~tpu_stencil.config
    .StreamConfig`): a progress record from a different geometry,
    filter, rep count or boundary must be refused, not resumed —
    the same discipline as :func:`_fingerprint`. The input spec is
    deliberately EXCLUDED: a resumed pipe has a different fd/path each
    run, and the sink identity (where the sidecar lives) already pins
    the artifact being continued."""
    return {
        "width": cfg.width,
        "height": cfg.height,
        "channels": cfg.channels,
        "filter": cfg.filter_name,
        "repetitions": cfg.repetitions,
        "boundary": cfg.boundary,
        "frames": cfg.frames,
    }


def save_stream_progress(cfg, frames_done: int,
                         mesh_devices: int = 1,
                         cursors: Optional[list] = None,
                         shard_frames: Optional[Tuple[int, int]] = None,
                         pipe_stages: int = 1
                         ) -> None:
    """Atomically record that frames [0, frames_done) are durably in
    the sink. No frame payload — unlike the rep checkpoints, a stream's
    completed frames already live in the output; progress is one
    integer plus the fingerprint.

    Mesh-fan runs (``mesh_devices > 1``) additionally record the device
    count and the per-device frame cursors (the next frame index each
    of the WRITING run's round-robin lanes would have received) —
    ``cursors[d]``, one per device. The in-order drain means
    ``frames_done`` alone pins global progress, and a resume re-deals
    the remaining frames from there (it does not re-adopt the recorded
    cursors — they are the diagnostic record of where the interrupted
    fan stood); what the resume contract enforces is the device count,
    which a different-count resume must refuse
    (:class:`MeshCursorMismatch`).

    Spatially-sharded runs (``--shard-frames``) record the RxC shard
    topology instead — the scatter layout every staged tile of the
    writing run followed. A resume under a different topology (or
    under no topology at all) must refuse typed rather than silently
    mis-scatter, the same contract as the fan's device count.

    Temporal-pipeline runs (``pipe_stages > 1``) record the stage
    count too — the three axes together pin the writing run's full
    placement, and a resume under any different axis value refuses
    typed (the recorded deal/scatter/fill discipline is only
    meaningful at the recorded topology)."""
    _checkpoint_fault(int(frames_done))
    path = _stream_paths(cfg)
    meta = dict(_stream_fingerprint(cfg), frames_done=int(frames_done))
    if mesh_devices > 1:
        meta["mesh_devices"] = int(mesh_devices)
        if cursors is not None:
            meta["device_cursors"] = [int(c) for c in cursors]
    if shard_frames is not None:
        meta["shard_frames"] = [int(d) for d in shard_frames]
    if pipe_stages > 1:
        meta["pipe_stages"] = int(pipe_stages)
    _write_meta(path, meta)


def _topology_str(shard) -> str:
    return "single-device" if shard is None else f"{shard[0]}x{shard[1]}"


def restore_stream_progress(cfg, mesh_devices: int = 1,
                            shard_frames: Optional[Tuple[int, int]] = None,
                            pipe_stages: int = 1
                            ) -> Optional[int]:
    """Frames already completed by a matching prior run, or None. A
    fingerprint mismatch raises (resuming a different job's sink would
    silently mix outputs); a device-count mismatch against a mesh-fan
    checkpoint — or a spatial-shard-topology mismatch against a
    ``--shard-frames`` checkpoint — raises typed
    (:class:`MeshCursorMismatch`: the recorded cursor/scatter layout is
    aligned to the writing run's topology, so a different one must
    never silently adopt it); a sidecar that fails its embedded CRC (or
    no longer parses) raises typed (:class:`CorruptCheckpoint` naming
    the file) — a flipped bit in ``frames_done`` would otherwise
    silently skip or rewrite frames."""
    path = _stream_paths(cfg)
    if not os.path.exists(path):
        return None
    meta = _load_meta(path)
    want = _stream_fingerprint(cfg)
    if {k: meta.get(k) for k in want} != want:
        raise ValueError(
            f"stream checkpoint at {path} was written for a different "
            f"job ({meta} != {want}); delete it or change --output"
        )
    recorded = int(meta.get("mesh_devices", 1))
    if recorded != int(mesh_devices):
        raise MeshCursorMismatch(recorded, int(mesh_devices), path)
    rec_shard = meta.get("shard_frames")
    rec_shard = tuple(int(d) for d in rec_shard) if rec_shard else None
    req_shard = tuple(int(d) for d in shard_frames) if shard_frames else None
    if rec_shard != req_shard:
        raise MeshCursorMismatch(
            f"spatial shard {_topology_str(rec_shard)} (--shard-frames)",
            (f"--shard-frames {_topology_str(req_shard)}"
             if req_shard else "single-device"),
            path,
        )
    rec_pipe = int(meta.get("pipe_stages", 1))
    if rec_pipe != int(pipe_stages):
        # The temporal-axis guard, same contract as the other two.
        raise MeshCursorMismatch(
            f"{rec_pipe} pipeline stage(s) (--pipe-stages)",
            f"--pipe-stages {int(pipe_stages)}",
            path,
        )
    return int(meta["frames_done"])


def clear_stream_progress(cfg) -> None:
    path = _stream_paths(cfg)
    if os.path.exists(path):
        os.remove(path)


def _stale_versions(data_path: str, before_rep: Optional[int] = None):
    """Versioned data files older than ``before_rep`` (all of them when
    None). Selecting by parsed rep number — NOT by "everything except the
    current file" — so a sweep can never race with another host already
    writing the NEXT rep's data file."""
    d = os.path.dirname(data_path) or "."
    prefix = os.path.basename(data_path) + ".r"
    for name in os.listdir(d):
        if not name.startswith(prefix):
            continue
        try:
            r = int(name[len(prefix):])
        except ValueError:
            continue
        if before_rep is None or r < before_rep:
            yield os.path.join(d, name)


def clear(cfg: JobConfig) -> None:
    """Remove checkpoint artifacts (called after a successful finish).
    Multi-host: only process 0 deletes (all writers are done by then)."""
    import jax

    if jax.process_index() != 0:
        return
    data_path, meta_path = _paths(cfg)
    for p in (data_path, meta_path):
        if os.path.exists(p):
            os.remove(p)
    for p in _stale_versions(data_path):
        os.remove(p)
