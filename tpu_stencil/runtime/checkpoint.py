"""Checkpoint / resume for long iterated-stencil runs.

The reference has no checkpointing (SURVEY.md §5 — intermediate repetitions
live only in its double buffers and a crash at rep 999/1000 loses
everything). Here the iteration state is just the current uint8 frame, so a
checkpoint is: the frame's raw bytes plus a JSON sidecar recording how many
repetitions it already contains and the config fingerprint. Writes are
atomic (tmp + rename), restores validate the fingerprint so a checkpoint
from a different image/filter/size is refused rather than silently resumed.

Enabled from the CLI via ``--checkpoint-every N`` / ``--resume``; the driver
splits the rep loop into N-rep chunks (still fully on-device — the chunking
only adds one host sync per N reps).
"""

from __future__ import annotations

import json
import os
from typing import Optional, Tuple

import numpy as np

from tpu_stencil.config import JobConfig
from tpu_stencil.io import native


def _paths(cfg: JobConfig) -> Tuple[str, str]:
    base = cfg.output_path + ".ckpt"
    return base, base + ".json"


def _fingerprint(cfg: JobConfig) -> dict:
    return {
        "image": os.path.abspath(cfg.image),
        "width": cfg.width,
        "height": cfg.height,
        "channels": cfg.channels,
        "filter": cfg.filter_name,
        "repetitions": cfg.repetitions,
        "frames": cfg.frames,
    }


def save(cfg: JobConfig, rep: int, frame: np.ndarray) -> None:
    """Atomically persist the frame as the state after ``rep`` repetitions."""
    data_path, meta_path = _paths(cfg)
    tmp = data_path + ".tmp"
    arr = np.ascontiguousarray(np.asarray(frame, np.uint8))
    native.pwrite_full(tmp, 0, arr.tobytes(), truncate=True)
    os.replace(tmp, data_path)
    meta = dict(_fingerprint(cfg), rep=rep)
    tmp_meta = meta_path + ".tmp"
    with open(tmp_meta, "w") as f:
        json.dump(meta, f)
    os.replace(tmp_meta, meta_path)


def restore(cfg: JobConfig) -> Optional[Tuple[int, np.ndarray]]:
    """Return (completed reps, frame) from a matching checkpoint, or None."""
    data_path, meta_path = _paths(cfg)
    if not (os.path.exists(data_path) and os.path.exists(meta_path)):
        return None
    with open(meta_path) as f:
        meta = json.load(f)
    want = _fingerprint(cfg)
    if {k: meta.get(k) for k in want} != want:
        raise ValueError(
            f"checkpoint at {data_path} was written for a different job "
            f"({meta} != {want}); delete it or change --output"
        )
    buf = native.pread_full(data_path, 0, cfg.nbytes)
    shape = (
        (cfg.height, cfg.width)
        if cfg.channels == 1
        else (cfg.height, cfg.width, cfg.channels)
    )
    if cfg.frames > 1:
        shape = (cfg.frames,) + shape
    frame = np.frombuffer(buf, np.uint8).reshape(shape)
    return int(meta["rep"]), frame


def clear(cfg: JobConfig) -> None:
    """Remove checkpoint artifacts (called after a successful finish)."""
    for p in _paths(cfg):
        if os.path.exists(p):
            os.remove(p)
