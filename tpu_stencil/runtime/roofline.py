"""Shared roofline model for the benchmark tools.

This workload is memory-bound (SURVEY.md §6: ~0.26 GFLOP/rep vs ~29 MB/rep
on the north star), so the honest headline is achieved HBM bytes/s against
the chip's peak — a row far off the roofline is a regression even when the
vs-GTX-970 speedup column looks flattering. Both ``bench.py`` and
``bench_sweep`` report through these helpers so the constants and the
traffic model cannot drift apart.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

V5E_HBM_GBPS = 819.0  # v5e HBM peak bandwidth
# v5e per-device HBM capacity (16 GiB) — the feasibility ceiling a
# single in-flight streaming frame (plus its donated output and the
# dispatch-ahead window's siblings) must fit under; past it the frame
# can only stream via --shard-frames. TPU_STENCIL_DEVICE_HBM_BYTES
# overrides (smaller lab parts, or tests pinning the bound).
V5E_HBM_BYTES = 16 * (1 << 30)
# v5e inter-chip interconnect: 4 links x 400 Gbps = 1600 Gbps aggregate
# per chip (the public spec sheet's number) — the ceiling the sharded
# path's ghost traffic rides.
V5E_ICI_GBPS = 200.0
# Host<->chip PCIe (Gen4 x16, ~32 GB/s each direction) — the ceiling
# the streaming engine's per-frame H2D/D2H transfers ride.
V5E_PCIE_GBPS = 32.0


def ici_ghost_bytes_per_edge(tile_shape, channels: int, halo: int,
                             mesh_shape, fuse: int = 1,
                             elem_bytes: int = 1,
                             mode: str = "phased") -> dict:
    """Per-edge breakdown of the modeled ICI ghost bytes *received per
    device per repetition*: ``{"n", "s", "w", "e"[, "corners"]}`` (keys
    only for edges that exchange — axes of size 1 exchange nothing).

    ``mode="phased"`` models the corner-routed two-phase exchange every
    joined schedule runs (off/split/fused-split, and the per-axis
    ppermutes of the monolithic step): the column strips ride the
    row-extended array, so W/E are ``tile_h + 2*g`` tall and corners
    travel inside them. ``mode="edge"`` models the partitioned per-edge
    pipeline: all four strips cover the BARE tile (W/E are ``tile_h``
    tall) and the four ``g x g`` corner patches arrive via the packed
    second hop, broken out as ``"corners"`` — per-edge bytes the
    ``--breakdown`` per-edge table and the multichip capture's ICI
    riders divide each measured edge span by. A fused chunk pays one
    exchange per ``fuse`` reps, so per-rep traffic divides by ``fuse``;
    ``g = fuse*halo`` is the strip depth.
    """
    th, tw = tile_shape
    r, c = mesh_shape
    g = fuse * halo
    scale = elem_bytes / max(1, fuse)
    per_edge = {}
    if r > 1:
        per_edge["n"] = per_edge["s"] = g * tw * channels * scale
    if c > 1:
        rows = th + (2 * g if (r > 1 and mode != "edge") else 0)
        per_edge["w"] = per_edge["e"] = g * rows * channels * scale
        if mode == "edge":
            per_edge["corners"] = 4 * g * g * channels * scale
    return per_edge


def ici_ghost_bytes_per_rep(tile_shape, channels: int, halo: int,
                            mesh_shape, fuse: int = 1,
                            elem_bytes: int = 1,
                            mode: str = "phased") -> float:
    """Total modeled ICI ghost bytes *received per device per
    repetition* on the sharded mesh — the comm side of the
    interior/border overlap schedules
    (:mod:`tpu_stencil.parallel.overlap`), shown by ``--breakdown``
    next to the measured exchange/interior/border probe spans. The sum
    of :func:`ici_ghost_bytes_per_edge` (see there for the per-mode
    strip geometry); ``elem_bytes``: 1 for the uint8 exchanges (the
    split/edge schedules, the Pallas chunk, direct plans), 4 for the
    monolithic XLA sep_int step's int32 phased exchange.
    """
    return float(sum(ici_ghost_bytes_per_edge(
        tile_shape, channels, halo, mesh_shape, fuse=fuse,
        elem_bytes=elem_bytes, mode=mode,
    ).values()))


def effective_fuse(filter_name: str, h_img: int,
                   block_h=None, fuse=None, schedule=None,
                   w_img=None, channels: int = 1, reps=None,
                   n_frames: int = 1) -> int:
    """The in-VMEM depth (reps per HBM round-trip)
    :func:`tpu_stencil.ops.pallas_stencil.iterate` will actually achieve
    for this (filter, image height) — HBM traffic per rep is divided by
    it. Mirrors iterate's clamp exactly (``block_h``/``fuse``: a
    forced/tuned geometry; None = module defaults). Under
    ``schedule='deep'`` this is the temporal-blocking depth: the full
    ``reps`` count when the resident kernel applies (``w_img``/
    ``channels`` feed its VMEM feasibility check; without a width the
    resident form is assumed infeasible), else the trapezoid depth the
    feasibility model picks. ``n_frames`` > 1 models the fused
    tall-image batch launch — residency is decided at the stacked
    clip's height (``frames_rows``), never per frame."""
    from tpu_stencil.models.blur import IteratedConv2D
    from tpu_stencil.ops import pallas_stencil as ps

    plan = IteratedConv2D(filter_name).plan
    if not ps._supported(plan):
        return 1
    rows = (
        ps.frames_rows(plan, h_img, n_frames) if n_frames > 1 else h_img
    )
    if schedule is not None and w_img:
        return ps.in_vmem_depth(plan, rows, w_img, channels,
                                schedule=schedule, block_h=block_h,
                                fuse=fuse, reps=reps)
    return ps.effective_geometry(plan, rows, block_h, fuse,
                                 schedule=schedule)[1]


def analytic_bytes_per_rep(frame_bytes: int, backend: str,
                           filter_name: str, h_img: int,
                           block_h=None, fuse=None, schedule=None,
                           w_img=None, channels: int = 1,
                           reps=None, n_frames: int = 1) -> float:
    """The traffic model's HBM bytes per repetition: the XLA step reads
    + writes the frame every rep; the fused Pallas kernel pays HBM once
    per in-VMEM depth reps — the effective fuse, or under
    ``schedule='deep'`` the full temporal-blocking depth (the whole
    ``reps`` loop for the resident kernel, the feasibility-chosen
    trapezoid depth otherwise; ghost-band overhead excluded — it is
    compute, not extra HBM traffic). This is the numerator of
    :func:`achieved` and the model side of the introspection
    cross-check (:func:`tpu_stencil.obs.introspect.cross_check`) — one
    formula, so the roofline and the XLA-vs-model audit can never
    disagree about what the model claims."""
    eff = (
        effective_fuse(filter_name, h_img, block_h, fuse,
                       schedule=schedule, w_img=w_img, channels=channels,
                       reps=reps, n_frames=n_frames)
        if backend == "pallas" else 1
    )
    return 2.0 * frame_bytes / eff


def achieved(frame_bytes: int, per_rep_s: float, backend: str,
             filter_name: str, h_img: int,
             block_h=None, fuse=None, schedule=None,
             w_img=None, channels: int = 1, reps=None
             ) -> Tuple[float, float]:
    """(HBM GB/s, % of v5e peak) for one measured per-rep time.

    ``block_h``/``fuse`` (and for deep runs ``schedule``/``w_img``/
    ``channels``/``reps``): what actually ran, when non-default — the
    traffic model must follow the launch, not the module defaults.
    """
    gbps = analytic_bytes_per_rep(
        frame_bytes, backend, filter_name, h_img, block_h, fuse,
        schedule=schedule, w_img=w_img, channels=channels, reps=reps,
    ) / per_rep_s / 1e9
    return gbps, 100 * gbps / V5E_HBM_GBPS


def stream_stage_seconds(frame_bytes: int, reps: int, backend: str,
                         filter_name: str, h_img: int,
                         block_h=None, fuse=None) -> dict:
    """Modeled per-frame seconds of the device-side streaming stages:
    ``h2d``/``d2h`` move one frame across PCIe, ``compute`` runs
    ``reps`` repetitions against the HBM roofline (the same
    :func:`analytic_bytes_per_rep` formula every other roofline view
    uses). Host ``read``/``write`` are *measured*, never modeled —
    there is no honest constant for arbitrary disks and pipes."""
    per_rep = analytic_bytes_per_rep(
        frame_bytes, backend, filter_name, h_img, block_h, fuse
    )
    return {
        "h2d": frame_bytes / (V5E_PCIE_GBPS * 1e9),
        "compute": reps * per_rep / (V5E_HBM_GBPS * 1e9),
        "d2h": frame_bytes / (V5E_PCIE_GBPS * 1e9),
    }


def stream_frames_per_second(frame_bytes: int, reps: int, backend: str,
                             filter_name: str, h_img: int,
                             block_h=None, fuse=None,
                             pipeline_depth: int = 2) -> float:
    """The modeled steady-state frames/s bound of the streaming
    pipeline (:mod:`tpu_stencil.stream`): with a dispatch-ahead window
    (``pipeline_depth`` >= 2) the stages overlap and the bound is
    ``1 / max(stage)``; at depth 1 the stages serialize and the bound
    degrades to ``1 / sum(stage)`` — the difference the pipeline
    exists to buy. Rendered next to the measured rate by the stream
    CLI's ``--breakdown`` (:func:`tpu_stencil.obs.breakdown
    .render_stream`)."""
    stages = stream_stage_seconds(
        frame_bytes, reps, backend, filter_name, h_img, block_h, fuse
    )
    bound = (
        sum(stages.values()) if pipeline_depth <= 1
        else max(stages.values())
    )
    return 1.0 / bound if bound > 0 else float("inf")


def device_hbm_bytes() -> int:
    """The per-device HBM feasibility budget:
    ``TPU_STENCIL_DEVICE_HBM_BYTES`` when set, else the v5e part's
    16 GiB."""
    return int(os.environ.get("TPU_STENCIL_DEVICE_HBM_BYTES",
                              V5E_HBM_BYTES))


def hbm_frame_feasible(frame_bytes: int, pipeline_depth: int = 2,
                       hbm_bytes: Optional[int] = None) -> bool:
    """Whether ONE device can hold the streaming engine's steady-state
    working set for this frame size: each of the ``pipeline_depth``
    in-flight frames occupies an input buffer that donation turns into
    its output (one resident canvas per window slot), plus one slot of
    H2D staging headroom — ``(depth + 1) * frame_bytes`` against the
    per-device budget (:func:`device_hbm_bytes`). False is the
    feasibility refusal the spatially-sharded stream route
    (``--shard-frames``) exists for: the per-device working set then
    shrinks by the mesh factor (each device holds TILES, not frames),
    and ``--shard-frames 0`` (auto) shards without paying a probe —
    the single-device arm could not run at all."""
    budget = hbm_bytes if hbm_bytes is not None else device_hbm_bytes()
    return (pipeline_depth + 1) * frame_bytes <= budget


def shard_tile_shape(h_img: int, w_img: int,
                     mesh_shape: Tuple[int, int]) -> Tuple[int, int]:
    """The padded per-device tile of a spatially sharded frame (the
    partition module's ceil-divide grid, restated jax-free so the
    roofline model needs no mesh)."""
    r, c = mesh_shape
    return -(-h_img // r), -(-w_img // c)


def sharded_stream_stage_seconds(reps: int,
                                 backend: str, filter_name: str,
                                 h_img: int, w_img: int, channels: int,
                                 mesh_shape: Tuple[int, int],
                                 halo: int = 1,
                                 block_h=None, fuse=None) -> dict:
    """Modeled per-frame seconds of the spatially-sharded streaming
    stages (``--shard-frames RxC``): ``h2d``/``d2h`` move the PADDED
    frame across the host's shared PCIe complex one per-shard tile at a
    time (the uploads are split per shard so frame i+1's tiles overlap
    frame i's exchange-and-compute, but they still sum to the padded
    frame on the one shared pipe — the per-shard PCIe term), and
    ``compute`` runs ``reps`` repetitions of the per-device TILE
    against the HBM roofline plus the per-rep ICI ghost traffic of the
    per-edge exchange (:func:`ici_ghost_bytes_per_rep`, ``mode="edge"``
    — the persistent per-edge pipeline the sharded stream threads
    through the rep loop). All byte counts derive from the tile
    geometry (``h_img``/``w_img``/``channels``), never a caller-
    supplied frame size that could disagree with it. Host
    ``read``/``write`` stay measured, never modeled."""
    th, tw = shard_tile_shape(h_img, w_img, mesh_shape)
    r, c = mesh_shape
    tile_bytes = th * tw * channels
    padded_bytes = tile_bytes * r * c
    per_rep_tile = analytic_bytes_per_rep(
        tile_bytes, backend, filter_name, th, block_h, fuse,
        w_img=tw, channels=channels, reps=reps,
    )
    ici_per_rep = ici_ghost_bytes_per_rep(
        (th, tw), channels, halo, mesh_shape, fuse=fuse or 1,
        mode="edge",
    )
    return {
        "h2d": padded_bytes / (V5E_PCIE_GBPS * 1e9),
        "compute": reps * (
            per_rep_tile / (V5E_HBM_GBPS * 1e9)
            + ici_per_rep / (V5E_ICI_GBPS * 1e9)
        ),
        "d2h": padded_bytes / (V5E_PCIE_GBPS * 1e9),
    }


def sharded_stream_frames_per_second(frame_bytes: int, reps: int,
                                     backend: str, filter_name: str,
                                     h_img: int, w_img: int,
                                     channels: int,
                                     mesh_shape: Tuple[int, int],
                                     halo: int = 1,
                                     block_h=None, fuse=None,
                                     pipeline_depth: int = 2) -> float:
    """The modeled steady-state frames/s bound of the spatially-sharded
    stream (:mod:`tpu_stencil.stream.sharded`): the max-stage bound of
    :func:`sharded_stream_stage_seconds` at depth >= 2 (per-shard H2D
    of frame i+1 overlaps frame i's exchange-and-compute), the serial
    sum at depth 1. One mesh computes one frame at a time, so unlike
    the fan-out there is no x-n_devices term — the speedup lives
    inside the stages (tile-sized compute, mesh-wide exchange).
    ``frame_bytes`` is accepted for signature parity with
    :func:`stream_frames_per_second` (the breakdown passes one info
    dict to both); the stage model derives every byte count from the
    tile geometry."""
    del frame_bytes
    stages = sharded_stream_stage_seconds(
        reps, backend, filter_name, h_img, w_img, channels,
        mesh_shape, halo=halo, block_h=block_h, fuse=fuse,
    )
    bound = (
        sum(stages.values()) if pipeline_depth <= 1
        else max(stages.values())
    )
    return 1.0 / bound if bound > 0 else float("inf")


def pcie_contention_frames_per_second(frame_bytes: int) -> float:
    """The host-side PCIe ceiling on whole-mesh streaming frames/s:
    every frame crosses the host's PCIe complex twice (H2D in, D2H
    out), and the fan-out's lanes share ONE host — so no matter how
    many chips compute, the host cannot move more than
    ``V5E_PCIE_GBPS / (2 * frame_bytes)`` frames per second through a
    single Gen4 x16 pipe. Deliberately independent of the device
    count: the model is the conservative shared-pipe shape (hosts with
    one PCIe root per chip would scale it, and then it simply never
    binds)."""
    return V5E_PCIE_GBPS * 1e9 / (2.0 * frame_bytes)


def mesh_stream_frames_per_second(frame_bytes: int, reps: int,
                                  backend: str, filter_name: str,
                                  h_img: int, block_h=None, fuse=None,
                                  pipeline_depth: int = 2,
                                  n_devices: int = 1) -> float:
    """The modeled whole-mesh steady-state frames/s bound of the mesh
    fan-out (:mod:`tpu_stencil.parallel.fanout`): frames are
    embarrassingly parallel, so the device-side bound is the
    single-device pipeline bound (max-stage, or serial sum at depth 1 —
    :func:`stream_frames_per_second`) times ``n_devices``, capped by
    the shared-host PCIe contention term
    (:func:`pcie_contention_frames_per_second`). Rendered next to the
    per-device bound by the stream CLI's ``--breakdown``."""
    per_device = stream_frames_per_second(
        frame_bytes, reps, backend, filter_name, h_img, block_h, fuse,
        pipeline_depth=pipeline_depth,
    )
    return min(per_device * max(1, n_devices),
               pcie_contention_frames_per_second(frame_bytes))


def pipeline_fill_drain_factor(frames: Optional[int],
                               pipe_stages: int) -> float:
    """The throughput fraction a K-stage temporal pipeline keeps after
    paying its fill and drain: a stream of F frames needs ``F + K - 1``
    ticks (the first ``K - 1`` outputs are fill garbage, the last
    ``K - 1`` ticks push zero-frames through to drain), so the achieved
    rate is ``F / (F + K - 1)`` of the steady-state tick rate. ``None``
    frames (until-EOF streams of unknown length) model as an infinite
    stream — factor 1.0; short explicit streams pay the full term, which
    is exactly why the auto knob must never enable the pipeline for a
    few-frame clip."""
    if frames is None or frames <= 0:
        return 1.0
    k = max(1, pipe_stages)
    return frames / float(frames + k - 1)


def pipeline_stream_stage_seconds(frame_bytes: int, reps: int,
                                  backend: str, filter_name: str,
                                  h_img: int, pipe_stages: int,
                                  block_h=None, fuse=None) -> dict:
    """Modeled per-TICK seconds of the temporal pipeline's streaming
    stages (``--pipe-stages K``): ``h2d``/``d2h`` still move one whole
    frame across PCIe per tick (a frame enters at stage 0 and leaves at
    stage K-1 every tick at steady state), while ``compute`` is one
    stage's share of the rep loop — ``ceil(reps / K)`` repetitions (the
    widest stage bounds the tick; contiguous slicing gives the early
    stages the remainder) against the HBM roofline, plus one whole-frame
    ICI hand-off to the next stage (the systolic shift every tick
    performs, absent at K=1). Host ``read``/``write`` stay measured,
    never modeled."""
    per_rep = analytic_bytes_per_rep(
        frame_bytes, backend, filter_name, h_img, block_h, fuse
    )
    k = max(1, pipe_stages)
    stage_reps = -(-reps // k)
    handoff = frame_bytes / (V5E_ICI_GBPS * 1e9) if k > 1 else 0.0
    return {
        "h2d": frame_bytes / (V5E_PCIE_GBPS * 1e9),
        "compute": stage_reps * per_rep / (V5E_HBM_GBPS * 1e9) + handoff,
        "d2h": frame_bytes / (V5E_PCIE_GBPS * 1e9),
    }


def pipeline_stream_frames_per_second(frame_bytes: int, reps: int,
                                      backend: str, filter_name: str,
                                      h_img: int, pipe_stages: int,
                                      frames: Optional[int] = None,
                                      block_h=None, fuse=None,
                                      pipeline_depth: int = 2) -> float:
    """The modeled frames/s bound of the temporal pipeline
    (:mod:`tpu_stencil.stream.pipelined`): the steady-state tick rate —
    max-stage of :func:`pipeline_stream_stage_seconds` at dispatch
    depth >= 2, serial sum at depth 1 — discounted by the fill/drain
    term :func:`pipeline_fill_drain_factor` for the stream length. At
    large ``reps`` the compute stage shrinks by ~K and the pipeline
    wins; at small ``reps`` the per-tick ICI hand-off plus the fill
    cost make it a modeled loss, and the auto knob must then never even
    probe it."""
    stages = pipeline_stream_stage_seconds(
        frame_bytes, reps, backend, filter_name, h_img, pipe_stages,
        block_h=block_h, fuse=fuse,
    )
    bound = (
        sum(stages.values()) if pipeline_depth <= 1
        else max(stages.values())
    )
    if bound <= 0:
        return float("inf")
    return pipeline_fill_drain_factor(frames, pipe_stages) / bound


def achieved_frames(frame_bytes: int, n_frames: int, per_rep_s: float,
                    backend: str, filter_name: str, h_img: int,
                    block_h=None, fuse=None) -> Tuple[float, float]:
    """(HBM GB/s, % of v5e peak) for a batched launch of ``n_frames``
    independent frames per rep — the serving engine's micro-batches and
    the ``--frames`` clip path. Frames are independent (no halo traffic
    between them), so traffic is simply ``n_frames`` times one frame's;
    ``h_img`` is the per-frame height the fused Pallas kernel tiles.
    """
    return achieved(frame_bytes * n_frames, per_rep_s, backend,
                    filter_name, h_img, block_h, fuse)
