"""Async micro-batching inference service (see docs/SERVING.md).

The request-level serving layer over the batch engine: a bounded queue
with backpressure, a micro-batching scheduler over shape buckets, an
executable cache, a double-buffered worker loop, and a metrics registry.

>>> from tpu_stencil.serve import StencilServer, ServeConfig
>>> with StencilServer(ServeConfig(max_queue=64)) as server:
...     out = server.submit(img_u8, reps=40).result()

CLI: ``python -m tpu_stencil serve --help`` (synthetic load generator,
``--self-test``, ``--stats-json``).
"""

from tpu_stencil.config import ServeConfig
from tpu_stencil.serve.engine import (
    QueueFull,
    ServerClosed,
    StencilServer,
    get_last_server,
)
from tpu_stencil.serve.metrics import Registry


def stats() -> dict:
    """Metrics snapshot of the most recently constructed live server."""
    server = get_last_server()
    if server is None:
        raise RuntimeError("no StencilServer has been constructed")
    return server.stats()


__all__ = [
    "QueueFull",
    "Registry",
    "ServeConfig",
    "ServerClosed",
    "StencilServer",
    "get_last_server",
    "stats",
]
