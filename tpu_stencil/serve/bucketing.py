"""Shape-bucket policy: pad heterogeneous requests onto a small ladder.

A stream of arbitrary (H, W, C) requests would compile one executable per
distinct shape — the compile amortization the whole serving layer exists
for would never land. Instead each spatial dim rounds UP to a ladder edge
(bottom/right zero-pad, the :func:`tpu_stencil.parallel.partition.pad_amounts`
semantics: the pad region is re-zeroed every repetition by the engine's
masked step, preserving exact zero-boundary results at the true edge).
Requests above the top edge pad to the next top-edge multiple, so no size
is ever refused for being big — only for the queue being full.

The batch axis is bucketed too (next power of two up to ``max_batch``,
short batches padded with zero frames): N distinct queue depths must not
mean N executables.

Everything here is jax-free and pure, so policy is unit-testable without
a backend.
"""

from __future__ import annotations

from typing import Sequence, Tuple

from tpu_stencil.parallel import partition

# Default spatial ladder. Starts at the sublane multiple (8), roughly
# x1.5 steps: adjacent real-world sizes share buckets while worst-case
# padded-pixel waste stays ~2.25x area (measured per request by the
# ``padded_pixels_total`` counter against ``image_pixels_total``).
DEFAULT_EDGES: Tuple[int, ...] = (
    8, 16, 24, 32, 48, 64, 96, 128, 192, 256, 384, 512, 768, 1024,
    1536, 2048, 3072,
)


def bucket_dim(n: int, edges: Sequence[int] = DEFAULT_EDGES) -> int:
    """Smallest ladder edge >= n; above the top edge, the next top-edge
    multiple (via ``partition.pad_amounts`` — same bottom/right pad math
    as the sharded mesh's indivisible-shape handling)."""
    if n < 1:
        raise ValueError(f"dim must be >= 1, got {n}")
    for e in edges:
        if n <= e:
            return e
    top = edges[-1]
    return n + partition.pad_amounts(n, 1, (top, 1))[0]


def bucket_shape(
    h: int, w: int, edges: Sequence[int] = DEFAULT_EDGES
) -> Tuple[int, int]:
    """The (bucket_h, bucket_w) canvas a (h, w) request is served in."""
    return bucket_dim(h, edges), bucket_dim(w, edges)


def batch_bucket(n: int, max_batch: int) -> int:
    """Padded batch size for n pending requests: next power of two,
    capped at ``max_batch`` (the scheduler never takes more than
    ``max_batch`` requests in one dispatch)."""
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    if n >= max_batch:
        return max_batch
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


def waste_pixels(
    true_shapes: Sequence[Tuple[int, int]], bucket_hw: Tuple[int, int],
    n_padded: int,
) -> int:
    """Padded-pixel overhead of one dispatched batch: bucket area beyond
    each request's true area, plus whole zero frames padding the batch
    axis. The HBM pipe moves these bytes for nothing — the waste counter
    is the cost side of the fewer-executables trade."""
    bh, bw = bucket_hw
    area = bh * bw
    real = sum(h * w for h, w in true_shapes)
    return area * n_padded - real
