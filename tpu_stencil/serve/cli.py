"""``python -m tpu_stencil serve`` — drive the serving engine.

Runs the synthetic load generator against an in-process
:class:`~tpu_stencil.serve.engine.StencilServer` and prints a throughput
/ tail-latency report (the serving analog of ``bench.py``'s single-job
capture). ``--self-test`` instead runs a deterministic correctness pass:
a handful of mixed-shape grey+RGB requests checked byte-for-byte against
the independent NumPy golden model, plus the backpressure and cache-hit
invariants — the smoke probe the verify recipe invokes.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from tpu_stencil.config import OVERLAP_MODES

# --stats-json payload schema. 1 = the PR-1 report dict plus the
# schema_version/ts fields themselves. Bump on breaking shape changes.
STATS_SCHEMA_VERSION = 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu_stencil serve",
        description="In-process async micro-batching inference service "
                    "driven by a synthetic load generator.",
    )
    p.add_argument("--self-test", action="store_true",
                   help="run the deterministic correctness/backpressure "
                        "smoke test and exit (0 = OK)")
    p.add_argument("--http", default=None, metavar="URL",
                   help="drive a NETWORK tier (python -m tpu_stencil "
                        "net) at URL instead of an in-process server: "
                        "the same closed/open load models (incl. "
                        "--rate-fps) POST raw frames at /v1/blur and "
                        "the report reads the tier's own /statusz "
                        "registry — identical schema, remote target "
                        "(docs/SERVING.md 'Network tier'). Engine flags "
                        "(--max-queue/--max-batch/--overlap/...) are "
                        "ignored: the tier's own CLI owns them")
    p.add_argument("--mode", default="closed", choices=["closed", "open"],
                   help="load model: closed (submit-and-wait workers) or "
                        "open (fixed-rate arrivals; overload rejects)")
    p.add_argument("--requests", type=int, default=64,
                   help="total synthetic requests (default 64)")
    p.add_argument("--concurrency", type=int, default=4,
                   help="closed-loop worker count (default 4)")
    p.add_argument("--rate", type=float, default=200.0,
                   help="open-loop arrival rate in req/s (default 200)")
    p.add_argument("--burst", type=int, default=1, metavar="N",
                   help="bursty open-loop arrivals: N simultaneous "
                        "same-shape requests per tick (distinct "
                        "payloads), tick gaps Poisson-jittered (seeded "
                        "exponential) at the same mean request rate — "
                        "the client shape that exercises cross-request "
                        "coalescing at the network edge (--http against "
                        "a --coalesce-window-us tier); p50/p99 report "
                        "next to achieved fps as always (default 1 = "
                        "the classic metronome; needs --mode open or "
                        "--rate-fps)")
    p.add_argument("--zipf", type=float, default=None, metavar="S",
                   help="keyspace mode: draw requests from a seeded "
                        "pool of --zipf-keys DISTINCT frames under a "
                        "Zipf(S) popularity law (S=0 uniform, S~1 "
                        "web-traffic skew) instead of all-distinct "
                        "frames — the repeat-heavy stream a "
                        "--result-cache-mb tier serves; the report "
                        "adds cache_hit_ratio from the target's own "
                        "result_cache_* counters. Deterministic per "
                        "--seed")
    p.add_argument("--zipf-keys", type=int, default=16, metavar="K",
                   help="distinct frames in the --zipf pool "
                        "(default 16)")
    p.add_argument("--ramp", default=None,
                   metavar="START_FPS:END_FPS:SECONDS",
                   help="ramped open-loop profile: sweep the offered "
                        "frame rate linearly from START_FPS to END_FPS "
                        "over SECONDS, stepped across --ramp-phases "
                        "equal metronome phases (arrivals due on "
                        "schedule regardless of completions — the "
                        "elastic-fleet acceptance load, docs/DEPLOY.md "
                        "'Elastic fleet runbook'); forces --mode open, "
                        "overrides --requests with the schedule's own "
                        "count, and reports per-phase achieved fps + "
                        "p99 from client-side records. Seeded; "
                        "exclusive with --rate-fps and --burst > 1")
    p.add_argument("--ramp-phases", type=int, default=4, metavar="N",
                   help="equal-duration phases the --ramp window is "
                        "stepped across (default 4)")
    p.add_argument("--rate-fps", type=float, default=None, metavar="FPS",
                   help="open-loop fixed-frame-rate mode: one frame due "
                        "every 1/FPS seconds regardless of completions "
                        "(the live-video arrival law; forces --mode "
                        "open at FPS); reports achieved vs requested "
                        "rate — the same loadgen shape the stream "
                        "benchmarks use (docs/STREAMING.md)")
    p.add_argument("--reps", type=int, default=5,
                   help="filter applications per request (default 5)")
    p.add_argument("--filter", dest="filter_name", default="gaussian",
                   help="filter name (default gaussian)")
    p.add_argument("--backend", default="auto",
                   choices=["auto", "xla", "pallas", "reference", "autotune"],
                   help="compute backend (default auto)")
    p.add_argument("--overlap", default="off", choices=list(OVERLAP_MODES),
                   help="interior/border overlap schedule (same vocabulary "
                        "as the run CLI); recorded in the overlap_mode "
                        "gauge. off (default) keeps every request on the "
                        "single-device bucket executables; any other mode "
                        "ACTIVATES sharded routing — requests of at least "
                        "--shard-min-pixels run the shard_map path over "
                        "all local devices under this schedule, bucketed "
                        "separately so small requests never wait inside a "
                        "sharded dispatch. Bit-exact either way "
                        "(docs/SERVING.md)")
    p.add_argument("--shard-min-pixels", dest="shard_min_pixels",
                   type=int, default=1 << 20, metavar="PX",
                   help="sharded-routing size threshold in true pixels "
                        "(H*W): with a non-off --overlap, requests at or "
                        "above it route through the spatially-sharded "
                        "path; below it they stay on the bucket "
                        "executables (default 1048576 = ~1024x1024)")
    p.add_argument("--request-timeout", dest="request_timeout_s",
                   type=float, default=0.0, metavar="SECONDS",
                   help="per-request deadline: a request still queued "
                        "past it fails typed (DeadlineExceeded) instead "
                        "of occupying a batch slot (0 = none; "
                        "docs/RESILIENCE.md)")
    p.add_argument("--verify", default=None, choices=["crc", "golden"],
                   help="check every completed response "
                        "(docs/RESILIENCE.md 'Integrity model'): crc "
                        "validates each body against the tier's "
                        "X-Result-Crc32c stamp (needs --http — only the "
                        "network tiers stamp) and stamps requests with "
                        "X-Content-Crc32c; golden compares small frames "
                        "against the independent NumPy golden (works "
                        "in-process too). Failures count "
                        "verify_failures_total in the report; the "
                        "closed loop fails fast on the first one")
    p.add_argument("--tenant", default=None, metavar="NAME",
                   help="stamp every request with this X-Tenant (needs "
                        "--http; the network tiers meter per-tenant "
                        "device-seconds and quota against it — "
                        "docs/OBSERVABILITY.md 'Cost attribution'); the "
                        "report gains a 'cost' rollup of the tier's "
                        "X-Cost-* response headers")
    p.add_argument("--witness-rate", dest="witness_rate", type=float,
                   default=0.0, metavar="RATE",
                   help="fraction of completed requests the in-process "
                        "engine re-executes through a different "
                        "measured-equivalent program (seeded; counted in "
                        "integrity_witness_*; 0 = off, the in-process "
                        "default — the net tier arms 1/256 fleet-wide)")
    p.add_argument("--faults", default=None, metavar="SPEC",
                   help="arm the fault-injection harness (chaos testing "
                        "/ failure reproduction); same grammar as "
                        "TPU_STENCIL_FAULTS, which this flag overrides")
    p.add_argument("--max-queue", type=int, default=256,
                   help="bounded queue depth; beyond it submissions are "
                        "rejected (default 256)")
    p.add_argument("--max-batch", type=int, default=8,
                   help="requests per micro-batch (default 8)")
    p.add_argument("--shapes", default="48x36,64x48,30x50",
                   help="comma-separated HxW request shapes to cycle")
    p.add_argument("--channels", default="3",
                   help="comma-separated channel counts to cycle "
                        "(1=grey, 3=rgb; default 3)")
    p.add_argument("--seed", type=int, default=0, help="loadgen seed")
    p.add_argument("--per-request", dest="per_request",
                   action="store_true",
                   help="print one line per completed request with its "
                        "latency and X-Trace-Id (the id every hop "
                        "echoed and /debug/trace assembles); the "
                        "summary always names the slowest trace")
    p.add_argument("--platform", default=None,
                   choices=["cpu", "tpu", "gpu"],
                   help="force the JAX platform before backend init")
    p.add_argument("--stats-json", default=None, metavar="PATH",
                   help="dump the report + metrics registry snapshot as "
                        "JSON to PATH ('-' = stdout); versioned schema "
                        "(schema_version + monotonic ts fields)")
    p.add_argument("--trace", default=None, metavar="PATH",
                   help="span tracing (tpu_stencil.obs): write a Chrome "
                        "trace-event JSON of the serve pipeline (enqueue/"
                        "batch-form/cache/execute/drain spans, one track "
                        "per thread) to PATH; works with --self-test too")
    p.add_argument("--metrics-text", default=None, metavar="PATH",
                   help="write the server's metrics registry as "
                        "Prometheus-style text exposition to PATH "
                        "('-' = stdout); includes the device-memory "
                        "gauges and (under --trace) the per-cache-entry "
                        "introspect_serve_bucket_* gauges")
    p.add_argument("--perf-log", nargs="?", const=None, default=False,
                   metavar="PATH",
                   help="append this loadgen run's p50 latency to the "
                        "perf-sentry history (default path: see "
                        "'python -m tpu_stencil perf --help'); gate "
                        "later runs with 'perf check'")
    return p


def _parse_shapes(parser, value):
    out = []
    for part in value.split(","):
        h, sep, w = part.strip().lower().partition("x")
        # "0".isdigit() is True: zero dims must die here as a usage error,
        # not as a bucketing traceback out of the worker thread.
        if (not sep or not h.isdigit() or not w.isdigit()
                or int(h) < 1 or int(w) < 1):
            parser.error(
                f"--shapes must be HxW[,HxW...] with positive integers, "
                f"got {value!r}"
            )
        out.append((int(h), int(w)))
    return tuple(out)


def self_test(metrics_text=None) -> int:
    """Deterministic smoke: golden-model exactness over mixed shapes and
    channel counts (including a 1-pixel image and an oversized-vs-ladder
    request), cache reuse, and backpressure rejection. ``metrics_text``:
    write the correctness server's registry as text exposition (the
    ``--metrics-text`` flag works under ``--self-test`` too)."""
    from tpu_stencil import filters
    from tpu_stencil.config import ServeConfig
    from tpu_stencil.ops import stencil
    from tpu_stencil.serve.engine import QueueFull, StencilServer

    rng = np.random.default_rng(7)
    cases = [
        (rng.integers(0, 256, (40, 30, 3), dtype=np.uint8), 3),
        (rng.integers(0, 256, (17, 23), dtype=np.uint8), 2),     # grey
        (rng.integers(0, 256, (1, 1), dtype=np.uint8), 1),       # 1 pixel
        (rng.integers(0, 256, (20, 44, 3), dtype=np.uint8), 0),  # identity
        # Sequential repeat of case 0's bucket: same executable key in a
        # later dispatch — must be a cache HIT, not a recompile.
        (rng.integers(0, 256, (40, 30, 3), dtype=np.uint8), 3),
    ]
    f = filters.get_filter("gaussian")
    with StencilServer(ServeConfig(max_queue=16, max_batch=4,
                                   bucket_edges=(8, 16, 32))) as server:
        for img, reps in cases:
            want = stencil.reference_stencil_numpy(img, f, reps)
            got = server.submit(img, reps).result(timeout=300)
            if not np.array_equal(got, want):
                print(f"serve self-test FAILED: shape={img.shape} "
                      f"reps={reps} mismatch", file=sys.stderr)
                return 1
        stats = server.stats()
    if metrics_text:
        from tpu_stencil.obs import exposition

        exposition.write_text(metrics_text, stats,
                              prefix="tpu_stencil_serve")
    if stats["counters"]["cache_hits_total"] < 1:
        print("serve self-test FAILED: no executable-cache hit",
              file=sys.stderr)
        return 1
    # Backpressure: a parked (never-started) server must reject, not grow.
    parked = StencilServer(ServeConfig(max_queue=2), start=False)
    img = cases[0][0]
    parked.submit(img, 1)
    parked.submit(img, 1)
    try:
        parked.submit(img, 1)
        print("serve self-test FAILED: full queue accepted a request",
              file=sys.stderr)
        return 1
    except QueueFull:
        pass
    if parked.stats()["counters"]["rejected_total"] != 1:
        print("serve self-test FAILED: rejection not counted",
              file=sys.stderr)
        return 1
    print(f"serve self-test OK: {len(cases)} requests exact, "
          f"cache_hits={stats['counters']['cache_hits_total']}, "
          f"batches={stats['counters']['batches_total']}, "
          "backpressure rejects when full")
    return 0


def _export_trace(path: str) -> None:
    from tpu_stencil import obs

    wrote = obs.export.write_chrome_trace(path, obs.get_tracer())
    if wrote:
        print(f"wrote trace {wrote}")


def main(argv=None) -> int:
    parser = build_parser()
    ns = parser.parse_args(argv)
    if ns.faults is not None:
        from tpu_stencil.resilience import faults as _faults

        try:
            _faults.configure(ns.faults)
        except ValueError as e:
            parser.error(str(e))
    if ns.platform:
        import jax

        jax.config.update("jax_platforms", ns.platform)
    if ns.trace:
        from tpu_stencil import obs

        obs.enable()
        # Traced serve runs also introspect each cache entry's compiled
        # executable (cost/memory analysis into the server registry —
        # one extra AOT compile per entry, docs/OBSERVABILITY.md).
        obs.introspect.enable()
    if ns.self_test:
        try:
            rc = self_test(metrics_text=ns.metrics_text)
            if ns.trace:
                _export_trace(ns.trace)
            return rc
        finally:
            if ns.trace:
                from tpu_stencil import obs

                obs.disable()
                obs.introspect.disable()

    from tpu_stencil.config import ServeConfig
    from tpu_stencil.serve import loadgen
    from tpu_stencil.serve.engine import StencilServer

    shapes = _parse_shapes(parser, ns.shapes)
    try:
        channels = tuple(int(c) for c in ns.channels.split(","))
        if not all(c in (1, 3) for c in channels):
            raise ValueError
    except ValueError:
        parser.error(f"--channels must be 1 and/or 3, got {ns.channels!r}")
    if ns.verify == "crc" and not ns.http:
        parser.error("--verify crc needs --http: only the network "
                     "tiers stamp X-Result-Crc32c (use --verify golden "
                     "for an in-process server)")
    if ns.tenant and not ns.http:
        parser.error("--tenant needs --http: only the network tiers "
                     "meter X-Tenant")
    if not ns.http:
        try:
            cfg = ServeConfig(
                filter_name=ns.filter_name, backend=ns.backend,
                max_queue=ns.max_queue, max_batch=ns.max_batch,
                overlap=ns.overlap,
                shard_min_pixels=ns.shard_min_pixels,
                request_timeout_s=ns.request_timeout_s,
                witness_rate=ns.witness_rate,
            )
        except ValueError as e:
            parser.error(str(e))
    try:
        if ns.rate_fps is not None and not ns.rate_fps > 0:
            parser.error(f"--rate-fps must be > 0, got {ns.rate_fps}")
        if ns.burst < 1:
            parser.error(f"--burst must be >= 1, got {ns.burst}")
        if ns.burst > 1 and ns.mode != "open" and ns.rate_fps is None:
            parser.error("--burst needs --mode open (or --rate-fps): "
                         "it is an open-loop arrival mode")
        if ns.zipf is not None and ns.zipf < 0:
            parser.error(f"--zipf must be >= 0, got {ns.zipf}")
        if ns.zipf_keys < 1:
            parser.error(f"--zipf-keys must be >= 1, got {ns.zipf_keys}")
        ramp = None
        if ns.ramp is not None:
            try:
                parts = ns.ramp.split(":")
                if len(parts) != 3:
                    raise ValueError
                ramp = tuple(float(v) for v in parts)
                if not all(v > 0 for v in ramp):
                    raise ValueError
            except ValueError:
                parser.error(
                    f"--ramp must be START_FPS:END_FPS:SECONDS with "
                    f"three positive numbers, got {ns.ramp!r}"
                )
            if ns.rate_fps is not None:
                parser.error("--ramp and --rate-fps are exclusive "
                             "arrival laws (the ramp sweeps the rate)")
            if ns.burst > 1:
                parser.error("--ramp is a metronome profile; "
                             "--burst > 1 is not supported with it")
            if ns.ramp_phases < 1:
                parser.error(f"--ramp-phases must be >= 1, "
                             f"got {ns.ramp_phases}")
        loadgen_kwargs = dict(
            mode=ns.mode, requests=ns.requests,
            concurrency=ns.concurrency, rate=ns.rate, reps=ns.reps,
            shapes=shapes, channels=channels, seed=ns.seed,
            rate_fps=ns.rate_fps, burst=ns.burst,
            verify=ns.verify, verify_filter=ns.filter_name,
            per_request=ns.per_request,
            zipf=ns.zipf, zipf_keys=ns.zipf_keys,
            ramp=ramp, ramp_phases=ns.ramp_phases,
        )
        if ns.http:
            # The network-tier target: same loops, same report schema,
            # remote fleet. No in-process server (and no jax import)
            # on this path — the tier owns the engines.
            target = loadgen.HttpTarget(ns.http, verify=ns.verify,
                                        tenant=ns.tenant)
            try:
                report = loadgen.run(target, **loadgen_kwargs)
            finally:
                target.close()
        else:
            with StencilServer(cfg) as server:
                report = loadgen.run(server, **loadgen_kwargs)
        if ns.trace:
            _export_trace(ns.trace)
    finally:
        if ns.trace:
            from tpu_stencil import obs

            obs.disable()
            obs.introspect.disable()
    if ns.metrics_text:
        from tpu_stencil.obs import exposition

        exposition.write_text(
            ns.metrics_text, report["stats"],
            prefix="tpu_stencil_net" if ns.http else "tpu_stencil_serve",
        )
    c = report["stats"]["counters"]
    if ns.per_request and report.get("per_request"):
        # The loadgen's per-request table: the X-Trace-Id column is
        # the same id every hop echoed, so a straggler line greps
        # straight to /debug/trace/<id> and its flightrec dump.
        print(f"{'i':>4}  {'latency_ms':>10}  {'ok':>2}  X-Trace-Id")
        for rec in report["per_request"]:
            print(f"{rec['i']:>4}  {rec['latency_s'] * 1e3:>10.2f}  "
                  f"{'y' if rec['ok'] else 'n':>2}  {rec['trace_id']}")
    print(
        f"served {report['completed']}/{report['requests']} requests "
        f"in {report['wall_seconds']:.3f}s "
        f"({report['throughput_rps']:.1f} req/s, {report['mode']}-loop"
        f"{', http' if ns.http else ''})"
    )
    if report.get("slowest_trace_id"):
        print(
            f"slowest request: "
            f"{report['slowest_latency_s'] * 1e3:.2f}ms "
            f"trace {report['slowest_trace_id']} "
            f"(GET /debug/trace/<id>; flightrec dump if it tripped a "
            f"trigger)"
        )
    if ns.http:
        print(
            f"latency p50={report['p50_s'] * 1e3:.2f}ms "
            f"p99={report['p99_s'] * 1e3:.2f}ms; "
            f"rejected={report['rejected']} "
            f"shed={c.get('shed_total', 0)} "
            f"fleet_batches={c.get('fleet_batches_total', 0)} "
            f"warm={c.get('warm_submits_total', 0)}"
        )
    else:
        print(
            f"latency p50={report['p50_s'] * 1e3:.2f}ms "
            f"p99={report['p99_s'] * 1e3:.2f}ms; "
            f"rejected={report['rejected']} batches={c['batches_total']} "
            f"cache={c['cache_hits_total']}h/{c['cache_misses_total']}m "
            f"padded_waste={c['padded_pixels_total']}px"
        )
    if "verify_failures_total" in report:
        print(
            f"verify ({report['verify']}): "
            f"{report['verify_failures_total']} failure(s) over "
            f"{report['completed']} completed"
        )
    if "cost" in report and report["cost"]["responses"]:
        cost = report["cost"]
        srcs = ", ".join(f"{k}={v}" for k, v in
                         sorted(cost["by_source"].items()))
        print(
            f"cost (tenant {cost['tenant']}): "
            f"{cost['device_seconds']:.4f}s device over "
            f"{cost['responses']} costed response(s), "
            f"queue {cost['queue_us'] / 1e6:.4f}s; source {srcs}"
        )
    if "zipf" in report:
        hr = report["cache_hit_ratio"]
        print(
            f"zipf keyspace: S={report['zipf']:g} over "
            f"{report['zipf_keys']} key(s), "
            f"{report['distinct_keys_offered']} distinct offered; "
            f"cache_hit_ratio="
            f"{'n/a (no result cache)' if hr is None else format(hr, '.3f')}"
        )
    if "requested_fps" in report:
        print(
            f"frame rate: requested {report['requested_fps']:.2f} fps, "
            f"offered {report['offered_fps']:.2f} fps, "
            f"achieved {report['achieved_fps']:.2f} fps"
        )
    if "ramp" in report:
        r = report["ramp"]
        print(
            f"ramp {r['start_fps']:g}->{r['end_fps']:g} fps over "
            f"{r['seconds']:g}s ({len(r['phases'])} phase(s)):"
        )
        for pi, ph in enumerate(r["phases"]):
            print(
                f"  phase {pi}: {ph['fps']:8.2f} fps requested, "
                f"{ph['achieved_fps']:8.2f} achieved "
                f"({ph['completed']}/{ph['requests']}), "
                f"p99={ph['p99_s'] * 1e3:.2f}ms"
            )
    if ns.perf_log is not False:
        # One sentry record per loadgen run: p50 request latency. The
        # load model (mode, per-request reps, and the closed-loop
        # concurrency / open-loop rate) changes what p50 *means*, so it
        # is folded into the metric name — a key field — and different
        # load shapes can never gate each other as false regressions.
        import jax

        from tpu_stencil.obs import sentry

        # report["mode"] (not ns.mode): --rate-fps forces the open loop
        # inside loadgen.run, and the sentry key must name what ran.
        ran_mode = report["mode"]
        if ran_mode == "closed":
            load = f"c{ns.concurrency}"
        elif ramp is not None:
            # A swept rate changes what p50 means phase to phase —
            # the whole profile is its own sentry series.
            load = f"ramp{ramp[0]:g}-{ramp[1]:g}"
        elif ns.rate_fps is not None:
            load = f"fps{ns.rate_fps:g}"
        else:
            load = f"rate{ns.rate:g}"
        if ns.burst > 1:
            # Bursty arrivals change what p50 means — own sentry series.
            load += f"b{ns.burst}"
        if ns.zipf is not None:
            # A repeat-heavy keyspace against a caching tier serves
            # hits in microseconds — its p50 is a different quantity,
            # so the zipf exponent is a sentry key field too.
            load += f"z{ns.zipf:g}"
        # The network tier measures HTTP+routing on top of the engine,
        # so its p50 is its own sentry series — never compared against
        # the in-process numbers as a false regression.
        tier = ".net" if ns.http else ""
        metric = f"serve.p50_s.{ran_mode}.{load}.reps{ns.reps}{tier}"
        if report["p50_s"] > 0:
            rec = sentry.make_record(
                metric=metric, value=report["p50_s"],
                filter_name=ns.filter_name, shape=ns.shapes,
                backend=ns.backend, platform=jax.default_backend(),
                source="serve",
                extra={"requests": report["requests"],
                       "throughput_rps": report["throughput_rps"]},
            )
            print(f"perf history += {metric} {report['p50_s']:.6g}s -> "
                  f"{sentry.append(rec, ns.perf_log)}")
        else:
            print("perf history not updated: no completed requests "
                  "(p50 unavailable)")
    if ns.stats_json:
        # Versioned schema: consumers (tools/bench_capture.py, dashboards)
        # dispatch on schema_version instead of guessing from key shape;
        # ts is monotonic so within-process captures order totally even
        # across wall-clock adjustments.
        report["schema_version"] = STATS_SCHEMA_VERSION
        report["ts"] = time.monotonic()
        payload = json.dumps(report, indent=2, sort_keys=True)
        if ns.stats_json == "-":
            print(payload)
        else:
            with open(ns.stats_json, "w") as fh:
                fh.write(payload + "\n")
            print(f"wrote {ns.stats_json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
