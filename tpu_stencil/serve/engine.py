"""In-process async micro-batching inference engine.

The reference (and ``driver.run_job``) is a batch program: one image in,
N reps, one image out. This module is the request-level serving layer the
ROADMAP's "heavy traffic" north star needs, built from three bounded
pieces:

* a **bounded request queue** with backpressure: ``submit`` on a full
  queue raises :class:`QueueFull` immediately (reject-with-error), it
  never buffers unboundedly — peak memory is
  ``O(max_queue + pipeline_depth * max_batch)`` frames by construction;
* a **micro-batching scheduler**: pending requests group by executable
  key — (filter, shape-bucket, dtype, backend, reps) — so every batch
  hits one cached jitted executable (:mod:`.bucketing` pads H/W onto a
  ladder and the batch axis to a power of two). Compilation and
  host<->device transfer amortize across the stream the way the
  persistent-MPI stencil work amortizes communication setup across
  repeated exchanges (PAPERS.md);
* a **double-buffered worker loop**: JAX dispatch is async, so the
  worker keeps up to ``pipeline_depth`` batches in flight — batch i+1's
  host-side padding + host->device transfer overlaps batch i's device
  compute, keeping the HBM pipe fed (the workload is memory-bound;
  throughput is pipe saturation, not per-request latency tricks).

Exactness: each bucket executable is the per-rep step of the existing
:mod:`tpu_stencil.models.blur` / :mod:`tpu_stencil.ops.pallas_stencil`
paths (input buffer donated for HBM double-buffering) with the pad
region re-zeroed every rep — the sharded runner's mask discipline — so
a request's cropped output is byte-identical to ``driver.run_job`` on
the same (image, filter, reps). ``tests/test_fuzz.py`` asserts this.
"""

from __future__ import annotations

import collections
import concurrent.futures
import dataclasses
import functools
import itertools
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

import numpy as np

from tpu_stencil.config import ServeConfig
from tpu_stencil.integrity import checksum as _checksum
from tpu_stencil.integrity import witness as _witness_mod
from tpu_stencil.obs import context as _obs_ctx
from tpu_stencil.obs import flight as _obs_flight
from tpu_stencil.obs import ledger as _obs_ledger
from tpu_stencil.obs import introspect as _introspect
from tpu_stencil.obs import span as _obs_span
from tpu_stencil.obs import tracing as _obs_tracing
from tpu_stencil.resilience import faults as _faults
from tpu_stencil.resilience import retry as _retry
from tpu_stencil.resilience.errors import DeadlineExceeded, WorkerCrashed
from tpu_stencil.serve import bucketing
from tpu_stencil.serve.metrics import Registry


def _resolve(fut: "concurrent.futures.Future", value=None,
             exc: Optional[BaseException] = None) -> bool:
    """Resolve ``fut`` with a result (or exception), tolerating a client
    cancel that lands between a ``done()`` check and the set: futures are
    never moved to RUNNING, so ``cancel()`` can win that race at any
    moment, and an unguarded ``set_result`` would raise
    InvalidStateError — which the worker loop's catch-all would then
    spread as a failure onto the whole batch. Returns True when the
    future actually took the value."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(value)
        return True
    except concurrent.futures.InvalidStateError:
        return False  # cancelled (or already resolved); drop silently


def _batch_trace_ids(batch) -> tuple:
    """The distinct trace ids riding in a batch (span-arg form): a
    dispatch/drain span covers requests from several traces, and the
    ``trace_ids`` arg is what lets ``/debug/trace`` and the flight
    dumps claim the batch-scope spans for each of them."""
    return tuple(sorted({r.trace_id for r in batch if r.trace_id}))


class QueueFull(RuntimeError):
    """Backpressure signal: the bounded request queue is at capacity.
    Callers retry later or shed load — the server never buffers more."""


class ServerClosed(RuntimeError):
    """The server is shutting down (or closed); no new work is accepted."""


@dataclasses.dataclass
class Request:
    """One queued inference request (internal)."""

    req_id: int
    image: Optional[np.ndarray]  # uint8 (H, W) or (H, W, C); None once
    #                              consumed into a batch canvas
    reps: int
    filter_name: str
    key: tuple                 # executable-cache key (sans batch bucket)
    bucket_hw: Tuple[int, int]
    future: concurrent.futures.Future
    t_submit: float
    # Absolute perf_counter deadline (None = none): expired requests
    # fail typed (DeadlineExceeded) at batch formation instead of
    # occupying a batch slot.
    t_deadline: Optional[float] = None
    # Routed through the spatially-sharded shard_map path (ServeConfig
    # overlap != "off" and the request is at least shard_min_pixels):
    # the key carries a "sharded" marker, so these requests bucket
    # separately and small requests never share a batch with (or wait
    # inside) a sharded dispatch.
    sharded: bool = False
    # Request correlation (obs.context): the trace context bound on the
    # submitting thread, carried so worker-side records (serve.request,
    # batch trace_ids args, anomaly dumps) stitch into the caller's
    # cross-process trace. Empty outside any request scope.
    trace_id: str = ""
    span_id: str = ""
    # The TRUE frame shape, kept past consumption: once the worker has
    # copied the pixels into the batch canvas it drops ``image`` (an
    # owned staging buffer goes back to its arena), but retire still
    # needs the crop geometry.
    shape: Tuple[int, ...] = ()
    # Zero-copy ownership (the HTTP ingest-arena contract): called
    # exactly once, on the worker thread, the moment the engine is done
    # reading ``image`` — the staging buffer may be reused after.
    on_consumed: Optional[object] = None
    # Witness input snapshot: the sampler picks at dispatch (the last
    # moment the input still exists for owned requests) and the copy
    # rides here until the retire-side re-execution.
    witness_src: Optional[np.ndarray] = None
    # Cost attribution (obs.ledger): the RequestLedger bound on the
    # submitting thread, carried like trace_id so the worker credits
    # queue wait and the batch's amortized device share without any
    # contextvar crossing threads. None outside a metered edge.
    ledger: Optional[_obs_ledger.RequestLedger] = None


@dataclasses.dataclass
class GroupItem:
    """One member of a router-coalesced group (:meth:`StencilServer.
    submit_group`): the future/deadline/trace identity was fixed at
    ADMISSION time on the HTTP handler thread; the engine only wraps it
    into a :class:`Request`. ``t_deadline`` is an absolute
    ``perf_counter`` instant — time spent forming the group counts
    against the member's deadline, never silently stretches it."""

    image: np.ndarray
    future: concurrent.futures.Future
    t_submit: float
    t_deadline: Optional[float] = None
    trace_id: str = ""
    span_id: str = ""
    on_consumed: Optional[object] = None
    ledger: Optional[_obs_ledger.RequestLedger] = None


def _mask_valid(imgs, valid_h, valid_w):
    """Per-frame validity mask for a padded (N, BH, BW[, C]) canvas:
    True inside each frame's true (h, w), False in the pad region."""
    import jax
    import jax.numpy as jnp

    n, bh, bw = imgs.shape[0], imgs.shape[1], imgs.shape[2]
    rows = jax.lax.broadcasted_iota(jnp.int32, (n, bh, bw), 1)
    cols = jax.lax.broadcasted_iota(jnp.int32, (n, bh, bw), 2)
    mask = (rows < valid_h[:, None, None]) & (cols < valid_w[:, None, None])
    if imgs.ndim == 4:
        mask = mask[..., None]
    return mask


def _build_bucket_executable(plan, backend: str, boundary: str,
                             interpret: bool, reps: int):
    """Compile-once callable for one cache key:
    ``fn(canvas_u8, valid_h, valid_w) -> canvas_u8`` (donates canvas).

    Per rep: vmapped single-application step (the XLA lowering's
    ``padded_step``, or the Pallas kernel's when the backend resolved to
    pallas), then the pad region re-zeroed via the validity mask —
    without the re-zero, pad pixels contaminated by rep k would leak back
    into the true image at rep k+1 (the same reason the sharded mesh
    masks its tile pad every iteration).

    ``reps`` is static (unlike ``blur.iterate``'s traced bound): the
    cache is keyed on reps by contract, so one entry == one compiled
    program and the hit/miss counters mean exactly "executable reused" /
    "compile paid". The canvas is donated — XLA ping-pongs two HBM
    buffers across the rep loop exactly like the single-job path.
    """
    import jax
    import jax.numpy as jnp

    from tpu_stencil.ops import lowering as _lowering

    if backend == "pallas":
        from tpu_stencil.ops import pallas_stencil

        def step(x):
            return pallas_stencil.padded_step(x, plan, interpret=interpret)
    else:
        def step(x):
            return _lowering.padded_step(x, plan, boundary)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(imgs, valid_h, valid_w):
        if reps == 0:
            return imgs
        mask = _mask_valid(imgs, valid_h, valid_w)
        vstep = jax.vmap(step)

        def body(_, x):
            return jnp.where(mask, vstep(x), jnp.uint8(0))

        return jax.lax.fori_loop(0, reps, body, imgs)

    return run


class _ExecutableCache:
    """Executable cache keyed on (filter, shape-bucket incl. batch
    bucket, dtype, backend, reps). A hit reuses a compiled program; a
    miss builds (and on first call compiles) a new one.

    LRU-bounded: the key space is client-controlled (reps, and oversized
    shapes pad to ever-larger top-edge multiples), so an unbounded map
    would leak compiled programs on a long-running server. Each entry
    owns its own ``jax.jit`` wrapper, so eviction really frees the
    compiled executable with it."""

    def __init__(self, registry: Registry, cap: int) -> None:
        self._entries: "collections.OrderedDict" = collections.OrderedDict()
        self._cap = cap
        # The worker owns the hot path; the lock exists for the
        # warm-start plane (keys/peek/seed run on HTTP threads while
        # the worker dispatches) and is uncontended otherwise.
        self._lock = threading.Lock()
        self._hits = registry.counter("cache_hits_total")
        self._misses = registry.counter("cache_misses_total")
        self._evictions = registry.counter("cache_evictions_total")

    def get(self, key, builder):
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._hits.inc()
                self._entries.move_to_end(key)
        if entry is not None:
            with _obs_span("serve.cache_hit", "serve"):
                pass  # zero-duration marker: this dispatch reused a program
            return entry
        self._misses.inc()
        # The miss span covers the builder, so the trace shows what a cold
        # key costs (jit wrapper construction; first-call compile lands
        # inside the batch's execute span).
        with _obs_span("serve.cache_miss", "serve"):
            entry = builder()
        with self._lock:
            self._entries[key] = entry
            while len(self._entries) > self._cap:
                self._entries.popitem(last=False)
                self._evictions.inc()
        return entry

    def keys(self) -> list:
        with self._lock:
            return list(self._entries.keys())

    def peek(self, key):
        """Read an entry without hit/miss accounting or LRU movement
        (the warm-state exporter is not a consumer)."""
        with self._lock:
            return self._entries.get(key)

    def seed(self, key, entry) -> bool:
        """Insert a PRE-BUILT entry without touching the hit/miss
        counters — the warm-start import path: the joiner's first real
        request must land as a counted HIT, and the import itself must
        never read as a compile paid.  An existing key is left alone
        (a locally built program always beats a shipped one); the LRU
        cap still holds."""
        with self._lock:
            if key in self._entries:
                return False
            self._entries[key] = entry
            while len(self._entries) > self._cap:
                self._entries.popitem(last=False)
                self._evictions.inc()
        return True

    def __len__(self) -> int:
        return len(self._entries)


class _CanvasArena:
    """Persistent per-bucket host canvases: the batch canvas (and its
    valid-h/valid-w vectors) for one (batch-bucket, bucket_hw, channels)
    key is a small RING of reusable buffers instead of a fresh
    ``np.zeros`` per dispatch — steady-state serving performs ZERO
    per-request host canvas allocations (the Casper thesis: the serving
    tax is data movement and allocation, not compute; the stream
    engine's staging-ring discipline applied to the batch path).

    The ring holds ``pipeline_depth + 1`` slots per key: at most
    ``pipeline_depth`` batches are dispatched-but-unretired at any
    moment (the worker loop's retire-when-full bound), so by the time a
    slot cycles back around its batch has retired — safe even where
    ``jax.device_put`` aliases host memory (CPU) and the donated launch
    ping-pongs through it.

    Keys are client-controlled (reps-independent, but oversized shapes
    pad to ever-larger buckets), so the key population is LRU-bounded
    like the executable cache; eviction frees the ring's buffers with
    it. Only the worker thread acquires, so no lock is needed —
    counters are thread-safe for scrapers.
    """

    _KEY_CAP = 32

    def __init__(self, registry: Registry, ring: int) -> None:
        self._rings: "collections.OrderedDict" = collections.OrderedDict()
        self._ring = max(2, int(ring))
        self._reuse = registry.counter("arena_canvas_reuse_total")
        self._alloc = registry.counter("arena_canvas_alloc_total")
        self._evict = registry.counter("arena_canvas_evictions_total")

    def acquire(self, shape: Tuple[int, ...]):
        """The next ``(canvas, valid_h, valid_w)`` slot for a batch of
        ``shape`` = (nb, bh, bw[, c]). A freshly allocated canvas is
        zeroed; a REUSED one is dirty — the dispatch writes every real
        slot's pixels and pad explicitly."""
        entry = self._rings.get(shape)
        if entry is None:
            entry = self._rings[shape] = {"slots": [], "next": 0}
            while len(self._rings) > self._KEY_CAP:
                self._rings.popitem(last=False)
                self._evict.inc()
        else:
            self._rings.move_to_end(shape)
        slots = entry["slots"]
        if len(slots) < self._ring:
            nb = shape[0]
            slot = (np.zeros(shape, np.uint8),
                    np.zeros(nb, np.int32), np.zeros(nb, np.int32))
            slots.append(slot)
            self._alloc.inc()
            return slot
        slot = slots[entry["next"]]
        entry["next"] = (entry["next"] + 1) % len(slots)
        self._reuse.inc()
        return slot


class _MemorySampler:
    """Background device-memory telemetry for a long-running server:
    a daemon thread samples ``device.memory_stats()`` every
    ``interval_s`` into the server registry as ``device_*`` gauges
    (bytes in use, allocator peak, limit — the registry's own
    high-water mark additionally tracks the sampled peak of
    bytes-in-use, so a scrape after a burst still shows how deep HBM
    got). On backends without allocator stats (CPU) the first probe
    returns None and NO thread is started — "unavailable" costs
    nothing. Started lazily from the worker thread so constructing a
    server never forces JAX backend init."""

    def __init__(self, registry: Registry, interval_s: float) -> None:
        self._registry = registry
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> bool:
        if self._interval <= 0 or self._thread is not None:
            return False
        # One synchronous probe decides availability (and seeds the
        # gauges so even a server shorter-lived than one interval
        # reports something).
        if _introspect.record_memory_gauges(self._registry) is None:
            return False
        self._thread = threading.Thread(
            target=self._loop, name="tpu-stencil-memsample", daemon=True,
        )
        self._thread.start()
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            _introspect.record_memory_gauges(self._registry)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


# Per-server bound on introspected cache keys: the key space is
# client-controlled (reps, ever-larger oversized shapes), so the
# bookkeeping set must not grow unboundedly on a long-armed server —
# past the cap, new keys simply go uncaptured (the cache's own LRU cap
# is 64 by default; 8x that covers realistic churn).
_INTROSPECT_KEY_CAP = 512

_server_serials = itertools.count()

_last_server_ref = None  # weakref to the most recently constructed server


class StencilServer:
    """The serving engine. Construct, ``submit`` from any thread, read
    ``stats()``, ``close()`` when done (also a context manager).

    >>> server = StencilServer(ServeConfig(max_queue=64, max_batch=8))
    >>> fut = server.submit(img_u8, reps=40)
    >>> out = fut.result()      # np.uint8, same shape as img_u8
    """

    def __init__(self, cfg: Optional[ServeConfig] = None,
                 start: bool = True) -> None:
        self.cfg = cfg or ServeConfig()
        if self.cfg.boundary != "zero":
            # Bucket padding re-zeroes the pad every rep, which preserves
            # ZERO semantics at the true edge; periodic would wrap at the
            # bucket-canvas edge and silently return wrong pixels (the
            # sharded runner refuses padded periodic grids for the same
            # reason). Fail at construction, never serve wrong data.
            raise NotImplementedError(
                "serve supports boundary='zero' only; periodic requests "
                "would wrap at the padded bucket edge, not the image edge"
            )
        self.registry = Registry()
        self._cache = _ExecutableCache(self.registry,
                                       self.cfg.max_executables)
        # Persistent host-side batch canvases: ring depth pipeline+1 so
        # a slot never cycles back before its batch retired (see
        # _CanvasArena).
        self._arena = _CanvasArena(self.registry,
                                   self.cfg.pipeline_depth + 1)
        self._models: Dict[str, object] = {}
        self._edges = self.cfg.bucket_edges or bucketing.DEFAULT_EDGES
        self._pending: "collections.deque[Request]" = collections.deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closing = False
        self._ids = itertools.count()
        self._worker: Optional[threading.Thread] = None
        # Worker-death propagation: when the worker thread dies from an
        # unhandled exception this holds it; every queued/in-flight
        # future fails with a typed WorkerCrashed and subsequent
        # submits are rejected with it (a crashed server stays
        # typed-dead until reconstructed).
        self._crashed: Optional[BaseException] = None
        # In-flight dispatched batches, owned by the worker loop but an
        # instance attribute so the death handler can fail their
        # futures (a local deque would strand them forever). Same for
        # the batch currently being dispatched/retired: it is neither
        # pending nor in-flight while the worker holds it, and a death
        # mid-dispatch must not strand it.
        self._inflight_batches: "collections.deque" = collections.deque()
        self._current_batch: List[Request] = []
        # Fault-injection sites resolved ONCE at construction (the
        # hot-path contract: with no faults armed every per-batch check
        # is a branch on a captured None).
        self._fault_h2d = _faults.site("h2d")
        self._fault_d2h = _faults.site("d2h")
        self._fault_compute = _faults.site("compute")
        self._fault_compile = _faults.site("compile")
        # Corruption site (integrity.checksum.fired converts the firing
        # into a bit flip in ONE request's result): the chaos stand-in
        # for a device/runtime returning wrong bytes with a 200.
        self._fault_corrupt_result = _faults.site("integrity.corrupt_result")
        # Witness re-execution (tpu_stencil.integrity): sampled
        # completed requests re-run through a different measured-
        # equivalent program AFTER their futures resolve (verification
        # must not stretch the tail) and verdicts go to on_witness —
        # the net tier points it at the router's quarantine board.
        self._witness = (
            _witness_mod.WitnessSampler(self.cfg.witness_rate,
                                        seed=self.cfg.witness_seed)
            if self.cfg.witness_rate > 0 else None
        )
        self.on_witness = None  # callable(ok: bool), set by the fleet
        # Compile-site introspection bookkeeping: cache keys whose
        # executable has been AOT-introspected (one capture per entry,
        # only while introspection is armed — see _dispatch_inner).
        # The serial tags this server's records in the process-global
        # introspect store, so introspection() never reports another
        # server's captures.
        self._serial = next(_server_serials)
        self._introspected: set = set()
        self._memsampler = _MemorySampler(
            self.registry, self.cfg.mem_sample_interval_s
        )
        # Metric handles (names are the docs/SERVING.md schema).
        m = self.registry
        self._m_requests = m.counter("requests_total")
        self._m_rejected = m.counter("rejected_total")
        self._m_completed = m.counter("completed_total")
        self._m_failed = m.counter("failed_total")
        self._m_batches = m.counter("batches_total")
        self._m_padded = m.counter("padded_pixels_total")
        self._m_real = m.counter("image_pixels_total")
        self._m_depth = m.gauge("queue_depth")
        self._m_inflight = m.gauge("inflight_batches")
        self._m_deadline = m.counter("deadline_expired_total")
        self._m_crashes = m.counter("resilience_worker_crashes_total")
        self._m_witness_total = m.counter("integrity_witness_total")
        self._m_witness_bad = m.counter("integrity_witness_mismatch_total")
        # Sharded routing (overlap != "off"): oversized requests run the
        # shard_map path; runners come from the PROCESS-SHARED cache in
        # parallel/sharded.py (one population for serve AND the stream's
        # --shard-frames route — a mesh program compiled by either
        # engine is a hit for the other; this server's hit/miss counters
        # land in its own registry).
        self._m_sharded = m.counter("sharded_requests_total")
        self._m_sharded_batches = m.counter("sharded_batches_total")
        # Cost attribution (obs.ledger / docs/OBSERVABILITY.md "Cost
        # attribution and capacity"): every retired batch's dispatch
        # wall splits into exactly one of goodput (request-kind work)
        # or overhead (warm/prewarm submits); witness re-executions add
        # overhead on top and are sub-counted so the conservation
        # equation stays solvable from a scrape.
        self._m_goodput = m.counter("goodput_device_seconds_total")
        self._m_overhead = m.counter("overhead_device_seconds_total")
        self._m_witness_s = m.counter("witness_device_seconds_total")
        self._m_h2d_bytes = m.counter("h2d_bytes_total")
        self._m_d2h_bytes = m.counter("d2h_bytes_total")
        self._m_qwait = m.histogram("queue_wait_seconds")
        self._m_blat = m.histogram("batch_latency_seconds")
        self._m_rlat = m.histogram("request_latency_seconds")
        self._m_bsize = m.histogram("batch_size")
        self._m_gbps = m.histogram("batch_hbm_gbps")
        # Configured overlap schedule, same gauge name/coding as the
        # sharded runner's (parallel/overlap.py MODE_CODES: off=0,
        # split=1, fused-split=2, edge=3), plus AUTO_CODE (4) for a
        # requested "auto" — recorded before the first sharded dispatch
        # resolves it against a real mesh (each ShardedRunner re-sets
        # the driver-registry gauge with its resolved mode). A non-off
        # mode activates sharded routing: requests of at least
        # cfg.shard_min_pixels run the shard_map path under this
        # schedule; "off" keeps everything on the bucket executables.
        from tpu_stencil.parallel import overlap as _overlap_mod

        m.gauge("overlap_mode").set(
            _overlap_mod.MODE_CODES.get(
                self.cfg.overlap, _overlap_mod.AUTO_CODE
            )
        )
        global _last_server_ref
        _last_server_ref = weakref.ref(self)
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        """Start the worker loop (idempotent). ``start=False`` at
        construction lets tests exercise backpressure with a parked
        queue. A pinned ``device_index`` is range-checked HERE (jax in
        hand), so a bad index is an immediate ValueError instead of a
        WorkerCrashed on the first batch."""
        if self.cfg.device_index is not None:
            import jax

            n = len(jax.local_devices())
            if self.cfg.device_index >= n:
                raise ValueError(
                    f"device_index {self.cfg.device_index} out of "
                    f"range: {n} local device(s)"
                )
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._worker_loop, name="tpu-stencil-serve",
                daemon=True,
            )
            self._worker.start()

    def close(self, timeout: Optional[float] = None) -> bool:
        """Stop accepting work, drain the queue, join the worker.

        Returns True when the server drained (the worker joined, or
        there was no live worker to join) and False when the join timed
        out and the worker was ABANDONED still running — counted in
        ``serve_close_abandoned_total`` so a fleet drain can report
        WHICH replica hung instead of silently returning. An abandoned
        worker keeps draining in the background (daemon thread); what
        the bool buys the caller is a truthful drain report within its
        deadline, never a hang."""
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        drained = True
        if self._worker is not None and self._worker.is_alive():
            self._worker.join(timeout)
            if self._worker.is_alive():
                drained = False
                self.registry.counter("serve_close_abandoned_total").inc()
        self._memsampler.stop()
        # No live worker to drain (never started, or already exited): a
        # queued future must never hang — fail it with the same error a
        # post-close submit gets. An ABANDONED worker (join timed out,
        # still running) keeps ownership of the queue: it is still
        # draining, and failing its pending requests out from under it
        # here would turn a slow drain into spurious ServerClosed
        # errors for requests that were about to complete.
        if drained:
            with self._lock:
                leftovers = list(self._pending)
                self._pending.clear()
                self._m_depth.set(0)
            for r in leftovers:
                if not r.future.done():
                    _resolve(r.future, exc=ServerClosed("server closed"))
        return drained

    def __enter__(self) -> "StencilServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission ----------------------------------------------------

    def submit(self, image: np.ndarray, reps: int,
               filter_name: Optional[str] = None,
               deadline_s: Optional[float] = None,
               owned: bool = False,
               on_consumed=None,
               ) -> "concurrent.futures.Future":
        """Enqueue one request; returns a Future resolving to the blurred
        uint8 array (same shape as ``image``). Raises :class:`QueueFull`
        when the queue is at capacity, :class:`ServerClosed` after
        ``close()``, and
        :class:`~tpu_stencil.resilience.errors.WorkerCrashed` when the
        worker thread died. ``deadline_s`` (default
        ``cfg.request_timeout_s``; 0/None = none) bounds how long the
        request may wait: expired requests fail typed with
        :class:`~tpu_stencil.resilience.errors.DeadlineExceeded` at
        batch formation instead of occupying a batch slot.

        ``owned=True`` is the zero-copy ingest contract: the caller
        guarantees the buffer is not mutated until the engine signals it
        is done reading (``on_consumed``, called once on the worker
        thread after the pixels were copied into the batch canvas), so
        the defensive copy is skipped — the HTTP staging-arena path.
        With ``owned=False`` (the default, every pre-existing caller)
        the engine copies as before and fires ``on_consumed``, if any,
        immediately after the copy."""
        image = np.asarray(image)  # no copy yet: validate + gate first
        if image.dtype != np.uint8:
            raise ValueError(f"image must be uint8, got {image.dtype}")
        if image.ndim not in (2, 3):
            raise ValueError(
                f"image must be (H, W) or (H, W, C), got shape {image.shape}"
            )
        if reps < 0:
            raise ValueError(f"reps must be >= 0, got {reps}")
        # Fast-path reject before the defensive copy: overload (the exact
        # scenario backpressure exists for) must not pay an O(H*W*C) copy
        # per shed request. The check repeats under the lock at append
        # time — this one only decides whether the copy is worth making.
        with self._cond:
            self._gate_locked()
        h, w = image.shape[:2]
        # Sharded routing: with a non-"off" overlap schedule, requests
        # at/above the size threshold run the spatially-sharded
        # shard_map path at their TRUE shape (the sharded runner's own
        # pad/mask discipline replaces bucket padding — a bucket canvas
        # would feed pad pixels to the mesh as image interior). The
        # "sharded" key marker buckets them separately, so small
        # requests never wait inside a sharded dispatch's batch.
        sharded = (
            self.cfg.overlap != "off"
            and h * w >= self.cfg.shard_min_pixels
        )
        # The sharded path stages inputs through its own runner.put,
        # which may alias host memory — owned buffers would be released
        # while the mesh still reads them. Copy there.
        if not owned or sharded:
            # Defensive copy: canvas assembly happens later on the
            # worker thread, so a caller reusing its buffer (the
            # frame-loop pattern) must not corrupt an already-queued
            # request. Mirrors the model's __call__ copy discipline.
            image = np.array(image, copy=True)
            if on_consumed is not None:
                # The caller's buffer is free the moment the copy landed.
                on_consumed()
                on_consumed = None
        fname = filter_name or self.cfg.filter_name
        channels = image.shape[2] if image.ndim == 3 else 1
        if sharded:
            bucket_hw = (h, w)
            key = (fname, (h, w), channels, str(image.dtype),
                   self.cfg.backend, int(reps), "sharded")
        else:
            bucket_hw = bucketing.bucket_shape(h, w, self._edges)
            # dtype is uint8 today across the whole pipeline; it is
            # part of the key by contract so a future f32 path can't
            # alias entries.
            key = (fname, bucket_hw, channels, str(image.dtype),
                   self.cfg.backend, int(reps))
        if deadline_s is None:
            deadline_s = self.cfg.request_timeout_s
        if deadline_s is not None and deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {deadline_s}")
        fut: concurrent.futures.Future = concurrent.futures.Future()
        now = time.perf_counter()
        ctx = _obs_ctx.current()
        req = Request(
            req_id=next(self._ids), image=image, reps=int(reps),
            filter_name=fname, key=key, bucket_hw=bucket_hw, future=fut,
            t_submit=now,
            t_deadline=(now + deadline_s) if deadline_s else None,
            sharded=sharded,
            trace_id=ctx.trace_id if ctx is not None else "",
            span_id=ctx.span_id if ctx is not None else "",
            shape=tuple(image.shape),
            on_consumed=on_consumed,
            ledger=_obs_ledger.current(),
        )
        with _obs_span("serve.enqueue", "serve", req_id=req.req_id):
            with self._cond:
                self._gate_locked()  # authoritative: at append time
                self._pending.append(req)
                self._m_requests.inc()
                self._m_depth.set(len(self._pending))
                self._cond.notify()
        return fut

    def submit_retrying(
        self, image: np.ndarray, reps: int,
        filter_name: Optional[str] = None,
        deadline_s: Optional[float] = None,
        policy: Optional["_retry.RetryPolicy"] = None,
        give_up_after_s: Optional[float] = 300.0,
    ) -> "concurrent.futures.Future":
        """:meth:`submit` under the shared retry policy
        (:mod:`tpu_stencil.resilience.retry`): :class:`QueueFull` is
        transient backpressure — back off and re-offer — while
        :class:`ServerClosed` / ``WorkerCrashed`` / validation errors
        raise immediately (the classifier knows the difference). The
        closed-loop client shape loadgen uses. ``give_up_after_s``
        bounds the total retry window regardless of the policy's
        attempt budget."""
        return _retry.reoffer_call(
            lambda: self.submit(image, reps, filter_name,
                                deadline_s=deadline_s),
            policy=policy, give_up_after_s=give_up_after_s,
            label="serve.submit",
        )

    def submit_group(self, items: List[GroupItem], reps: int,
                     filter_name: Optional[str] = None) -> None:
        """Enqueue a router-coalesced group under ONE lock acquisition
        — the continuous-batching primitive. All members enter the
        pending queue atomically (the worker cannot observe a partial
        group), so a same-key group of K <= max_batch rides one batch
        formation, one compiled program, one H2D, instead of K.

        Admission is all-or-nothing: if the queue cannot take the whole
        group, :class:`QueueFull` raises and NO member entered (the
        router re-offers the intact group to a sibling replica).
        Members keep their admission-time futures, deadlines and trace
        ids; validation failures raise :class:`ValueError` for the
        whole group (the members were pre-validated at the HTTP edge,
        so a failure here is a router bug, not client traffic).

        Member images are OWNED (the coalescer holds staging leases /
        immutable body views until ``on_consumed``) — no defensive
        copies, the zero-copy contract of ``submit(owned=True)``."""
        if reps < 0:
            raise ValueError(f"reps must be >= 0, got {reps}")
        fname = filter_name or self.cfg.filter_name
        with self._cond:
            self._gate_locked()
        reqs: List[Request] = []
        for it in items:
            image = np.asarray(it.image)
            if image.dtype != np.uint8 or image.ndim not in (2, 3):
                raise ValueError(
                    f"group member must be a uint8 (H, W[, C]) frame, "
                    f"got {image.dtype} {image.shape}"
                )
            h, w = image.shape[:2]
            channels = image.shape[2] if image.ndim == 3 else 1
            on_consumed = it.on_consumed
            sharded = (
                self.cfg.overlap != "off"
                and h * w >= self.cfg.shard_min_pixels
            )
            if sharded:
                # Same aliasing guard as submit(owned=True): the mesh
                # stages through runner.put, so keep the engine's copy.
                image = np.array(image, copy=True)
                if on_consumed is not None:
                    on_consumed()
                    on_consumed = None
                bucket_hw = (h, w)
                key = (fname, (h, w), channels, str(image.dtype),
                       self.cfg.backend, int(reps), "sharded")
            else:
                bucket_hw = bucketing.bucket_shape(h, w, self._edges)
                key = (fname, bucket_hw, channels, str(image.dtype),
                       self.cfg.backend, int(reps))
            reqs.append(Request(
                req_id=-1, image=image, reps=int(reps),
                filter_name=fname, key=key, bucket_hw=bucket_hw,
                future=it.future, t_submit=it.t_submit,
                t_deadline=it.t_deadline, sharded=sharded,
                trace_id=it.trace_id, span_id=it.span_id,
                shape=tuple(image.shape), on_consumed=on_consumed,
                ledger=it.ledger,
            ))
        with _obs_span("serve.enqueue_group", "serve", group=len(reqs)):
            with self._cond:
                self._gate_locked()  # authoritative: at append time
                if len(self._pending) + len(reqs) > self.cfg.max_queue:
                    self._m_rejected.inc(len(reqs))
                    raise QueueFull(
                        f"queue cannot take a group of {len(reqs)} "
                        f"({len(self._pending)}/{self.cfg.max_queue} "
                        f"pending); retry later"
                    )
                for r in reqs:
                    r.req_id = next(self._ids)
                    self._pending.append(r)
                self._m_requests.inc(len(reqs))
                self._m_depth.set(len(self._pending))
                self._cond.notify()

    def _gate_locked(self) -> None:
        """Admission gate (caller holds the lock): raises
        :class:`WorkerCrashed` / :class:`ServerClosed` /
        :class:`QueueFull` (counted) when the request must not enter."""
        if self._crashed is not None:
            raise WorkerCrashed(
                f"serve worker thread died "
                f"({type(self._crashed).__name__}: {self._crashed}); "
                "construct a new server"
            )
        if self._closing:
            raise ServerClosed("server is closed")
        if len(self._pending) >= self.cfg.max_queue:
            self._m_rejected.inc()
            raise QueueFull(
                f"queue full ({self.cfg.max_queue} pending); retry later"
            )

    # -- introspection -------------------------------------------------

    def stats(self) -> dict:
        """Snapshot of the metrics registry (docs/SERVING.md schema)."""
        snap = self.registry.snapshot()
        snap["executables_cached"] = len(self._cache)
        snap["introspected_executables"] = len(self._introspected)
        # The PROCESS-SHARED runner population (serve + stream share
        # one cache — parallel/sharded.py).
        from tpu_stencil.parallel import sharded as _sharded

        snap["sharded_runners_cached"] = _sharded.runner_cache_len()
        return snap

    def introspection(self) -> List[dict]:
        """THIS server's per-cache-entry compiled-artifact records (the
        ``serve.bucket`` site captures; see docs/OBSERVABILITY.md). The
        introspect store is process-global, so records are filtered by
        this server's serial — two servers in one process never see
        each other's captures here."""
        return [r for r in _introspect.records()
                if r.get("site") == "serve.bucket"
                and r.get("meta", {}).get("server") == self._serial]

    # -- warm-start plane (tpu_stencil.ctrl.warmstart) -----------------

    def warm_keys(self) -> list:
        """This server's executable-cache keys, for the exporter."""
        return self._cache.keys()

    def warm_entry(self, key):
        """One cached executable, without hit/miss/LRU side effects."""
        return self._cache.peek(key)

    def warm_seed(self, key, entry) -> bool:
        """Seed one pre-built executable (counter-silent; see
        ``_ExecutableCache.seed``)."""
        return self._cache.seed(key, entry)

    def export_warm_state(self) -> dict:
        """Serialize this server's executable cache into the
        warm-state envelope (ctrl/warmstart.py) for a joining host."""
        from tpu_stencil.ctrl import warmstart as _warmstart

        return _warmstart.export_server(self)

    def import_warm_state(self, payload) -> dict:
        """Import a warm-state envelope; every unusable artifact
        degrades to cold compile, typed and counted
        (``ctrl_warmstart_fallbacks_total``), never an error."""
        from tpu_stencil.ctrl import warmstart as _warmstart

        return _warmstart.import_server(self, payload)

    # -- scheduler / worker --------------------------------------------

    def _take_batch_locked(self) -> Tuple[List[Request], List[Request]]:
        """Pop the next micro-batch: the oldest request's executable key
        (FIFO fairness), joined by up to ``max_batch - 1`` same-key
        followers. O(pending) scan — pending is bounded by max_queue.

        Returns ``(batch, expired)``: requests whose deadline passed are
        swept out of the queue here (never occupying a batch slot) and
        handed back for the caller to fail typed OUTSIDE the lock —
        resolving a future runs client ``add_done_callback`` hooks,
        which must not run under the server lock."""
        if not self._pending:
            return [], []
        expired: List[Request] = []
        with _obs_span("serve.batch_form", "serve"):
            now = time.perf_counter()
            key = None
            batch: List[Request] = []
            kept: "collections.deque[Request]" = collections.deque()
            while self._pending:
                r = self._pending.popleft()
                if r.t_deadline is not None and now > r.t_deadline:
                    expired.append(r)
                    continue
                if key is None:
                    key = r.key
                if r.key == key and len(batch) < self.cfg.max_batch:
                    batch.append(r)
                else:
                    kept.append(r)
            self._pending = kept
            self._m_depth.set(len(self._pending))
        return batch, expired

    def _model_for(self, filter_name: str):
        from tpu_stencil.models.blur import IteratedConv2D

        model = self._models.get(filter_name)
        if model is None:
            model = self._models[filter_name] = IteratedConv2D(
                filter_name, backend=self.cfg.backend,
                boundary=self.cfg.boundary,
            )
        return model

    def _sharded_runner_for(self, filter_name: str, hw: Tuple[int, int],
                            channels: int):
        """The cached :class:`~tpu_stencil.parallel.sharded
        .ShardedRunner` for one true (filter, H, W, channels) — keyed
        WITHOUT reps (the runner's rep count is a traced argument, so
        one compiled mesh program serves any reps), resolved through
        the PROCESS-SHARED runner cache
        (:func:`tpu_stencil.parallel.sharded.shared_runner` — one
        LRU-bounded population serving this engine and the stream's
        ``--shard-frames`` route, so the same mesh program is never
        compiled twice in one process). Built over all local devices
        with the server's overlap schedule (a 1-device process degrades
        to the 1x1 mesh — still bit-exact, so routing never depends on
        device count).

        Returns None when the mesh CANNOT serve this geometry (e.g. an
        extreme aspect ratio whose per-device tile is smaller than the
        filter halo — a typed ValueError/NotImplementedError from the
        runner): the caller falls back to the single-device bucket
        path, which serves every shape the pre-routing engine did. The
        verdict is cached so retries of the same shape never re-pay the
        failed build."""
        import jax

        from tpu_stencil.parallel import sharded as _sharded

        def wrapper(build):
            with _obs_span("serve.sharded_runner_build", "serve",
                           shape=hw, channels=channels):
                # The largest compile in serve: the "compile" injection
                # point must cover it like the bucket builders, or the
                # chaos suite cannot exercise a failed mesh build.
                if self._fault_compile is not None:
                    self._fault_compile()
                return build()

        return _sharded.shared_runner(
            self._model_for(filter_name), hw, channels,
            devices=jax.devices(), overlap=self.cfg.overlap,
            registry=self.registry, build_wrapper=wrapper,
        )

    def _account_devices(self, n_devices: int, total_bytes: int,
                         n_requests: int, first: int = 0) -> None:
        """Per-device admission accounting: every dispatch charges each
        device it lands on — ``device_requests_total_dev<i>`` (a
        sharded request occupies every mesh device; a bucket batch
        occupies its pinned device — ``cfg.device_index``, else device
        0) and ``device_bytes_dispatched_total_dev<i>`` (its share of
        the dispatched bytes) — so a dashboard sees how admission
        spreads load across the mesh, not just an aggregate that hides
        an idle fan."""
        per = total_bytes // max(1, n_devices)
        for i in range(first, first + n_devices):
            self.registry.counter(
                f"device_requests_total_dev{i}"
            ).inc(n_requests)
            self.registry.counter(
                f"device_bytes_dispatched_total_dev{i}"
            ).inc(per)

    def _dispatch(self, batch: List[Request]):
        """Assemble the padded canvas and launch the bucket executable
        (async under JAX dispatch) — or, for a sharded-routed batch,
        launch each request's mesh program. Returns the retire
        closure's state: (batch, out_dev, meta, t_start)."""
        with _obs_span("serve.execute", "serve", batch=len(batch),
                       reps=batch[0].reps,
                       sharded=batch[0].sharded,
                       trace_ids=_batch_trace_ids(batch)):
            if batch[0].sharded:
                return self._dispatch_sharded(batch)
            return self._dispatch_inner(batch)

    def _consume(self, r: Request) -> None:
        """The engine is done reading ``r.image``: snapshot the witness
        input if the sampler picks this request (the input must outlive
        the staging buffer), release the buffer back to its owner, and
        drop the reference. Worker-thread only."""
        if self._witness is not None and self._witness.pick():
            r.witness_src = np.array(r.image, copy=True)
        cb = r.on_consumed
        r.image = None
        r.on_consumed = None
        if cb is not None:
            try:
                cb()
            except Exception:
                pass  # a broken release hook must not kill the batch

    def _dispatch_sharded(self, batch: List[Request]):
        """The oversized-request path: each request runs the shard_map
        + overlap program at its TRUE shape over all local devices
        (``ShardedRunner.put`` pads to the tile grid and the mask
        re-zeroes the pad every rep — bit-exact vs the bucket path).
        All launches are async dispatch; the retire fences them in
        order, so batch-mates pipeline on the mesh."""
        h, w = batch[0].image.shape[:2]
        channels = (
            batch[0].image.shape[2] if batch[0].image.ndim == 3 else 1
        )
        runner = self._sharded_runner_for(
            batch[0].filter_name, (h, w), channels
        )
        if runner is None:
            # The mesh cannot serve this geometry: fall back to the
            # bucket path, which serves every shape the pre-routing
            # engine did. Re-bucket the requests in place — the key
            # keeps its "sharded" marker (still a unique, consistent
            # cache key for this shape+reps), only the dispatch route
            # changes.
            for r in batch:
                r.sharded = False
                r.bucket_hw = bucketing.bucket_shape(h, w, self._edges)
            return self._dispatch_inner(batch)
        n_dev = int(runner.mesh.devices.size)
        t0 = time.perf_counter()
        if self._fault_h2d is not None:
            self._fault_h2d()
        if self._fault_compute is not None:
            self._fault_compute()
        outs = []
        for r in batch:
            dev = runner.put(r.image)
            outs.append(runner.run(dev, r.reps))
            self._consume(r)  # sharded images are engine-owned copies
        self._m_sharded.inc(len(batch))
        self._m_sharded_batches.inc()
        self._m_real.inc(len(batch) * h * w)
        ph, pw = runner.padded_shape
        self._m_padded.inc(len(batch) * (ph * pw - h * w))
        self._account_devices(
            n_dev, len(batch) * ph * pw * channels, len(batch)
        )
        for r in batch:
            self._m_qwait.observe(t0 - r.t_submit)
            if r.ledger is not None:
                r.ledger.add_queue(t0 - r.t_submit)
        self._m_bsize.observe(len(batch))
        meta = {"sharded": True, "runner": runner,
                "backend": runner.backend, "n_devices": n_dev}
        return batch, outs, meta, t0

    def _dispatch_inner(self, batch: List[Request]):
        import jax

        bh, bw = batch[0].bucket_hw
        channels = (
            batch[0].image.shape[2] if batch[0].image.ndim == 3 else 1
        )
        nb = bucketing.batch_bucket(len(batch), self.cfg.max_batch)
        shape = (nb, bh, bw) + ((channels,) if channels > 1 else ())
        # Persistent canvas (zero steady-state host allocation): a
        # reused slot is DIRTY, so every real slot writes its pixels AND
        # re-zeroes its pad explicitly — the pad must be zero at rep 1
        # (the masked step re-zeroes it only from rep boundaries on).
        # Unused batch-pad slots only need vh=vw=0: their pixels never
        # feed a real frame (vmap is per-frame) and are never cropped.
        canvas, vh, vw = self._arena.acquire(shape)
        for i, r in enumerate(batch):
            h, w = r.image.shape[:2]
            canvas[i, :h, :w] = r.image
            if h < bh:
                canvas[i, h:] = 0
            if w < bw:
                canvas[i, :h, w:] = 0
            vh[i], vw[i] = h, w
            self._consume(r)
        vh[len(batch):] = 0
        vw[len(batch):] = 0
        true_shapes = [r.shape[:2] for r in batch]
        self._m_padded.inc(bucketing.waste_pixels(true_shapes, (bh, bw), nb))
        self._m_real.inc(sum(h * w for h, w in true_shapes))
        # Bucket batches run single-device: the whole canvas lands on
        # the pinned device (cfg.device_index; default device 0) —
        # same per-device accounting the sharded path spreads across
        # its mesh, so a fleet's replicas charge their own chips.
        self._account_devices(1, int(canvas.nbytes), len(batch),
                              first=self.cfg.device_index or 0)

        model = self._model_for(batch[0].filter_name)
        backend, _sched = model.resolved_config((bh, bw), channels)
        if backend == "pallas":
            from tpu_stencil.ops import pallas_stencil

            if not pallas_stencil.plan_supported(model.plan, channels):
                backend = "xla"
        interpret = jax.default_backend() == "cpu"
        reps = batch[0].reps

        def builder():
            if self._fault_compile is not None:
                self._fault_compile()
            return _build_bucket_executable(
                model.plan, backend, self.cfg.boundary, interpret, reps
            )

        exe_key = batch[0].key + (nb,)
        exe = self._cache.get(exe_key, builder)
        t0 = time.perf_counter()
        if self._fault_h2d is not None:
            self._fault_h2d()
        if self._fault_compute is not None:
            self._fault_compute()
        # Explicit transfer, then launch: under async dispatch both return
        # immediately, so the NEXT batch's host-side assembly (and its
        # transfer) overlaps this batch's device compute. With a pinned
        # device (cfg.device_index — the replica-fleet contract) every
        # input is committed there, so the donated launch runs on that
        # chip; N replicas on N devices then compute truly in parallel.
        pin = None
        if self.cfg.device_index is not None:
            devices = jax.local_devices()
            if self.cfg.device_index >= len(devices):
                raise ValueError(
                    f"device_index {self.cfg.device_index} out of range: "
                    f"{len(devices)} local device(s)"
                )
            pin = devices[self.cfg.device_index]
        # device_put takes the numpy arrays directly: host -> pin in one
        # transfer (a jnp.asarray first would stage the canvas through
        # the DEFAULT device, serializing every replica on device 0).
        canvas_dev = jax.device_put(canvas, pin)
        vh_dev = jax.device_put(vh, pin)
        vw_dev = jax.device_put(vw, pin)
        if (_introspect.enabled() and exe_key not in self._introspected
                and len(self._introspected) < _INTROSPECT_KEY_CAP):
            # One AOT capture per cache entry (cost/memory analysis,
            # compile wall-time) into the server registry. Must lower
            # BEFORE the launch: the executable donates the canvas, and
            # a donated-away buffer cannot be lowered against.
            self._introspected.add(exe_key)
            _introspect.capture(
                "serve.bucket", exe, canvas_dev, vh_dev, vw_dev,
                meta={"server": self._serial,
                      "filter": batch[0].filter_name,
                      "bucket_hw": (bh, bw), "channels": channels,
                      "batch_bucket": nb, "reps": reps,
                      "backend": backend},
                registry=self.registry,
            )
        out_dev = exe(canvas_dev, vh_dev, vw_dev)
        for r in batch:
            self._m_qwait.observe(t0 - r.t_submit)
            if r.ledger is not None:
                r.ledger.add_queue(t0 - r.t_submit)
        self._m_bsize.observe(len(batch))
        return (batch, out_dev,
                (bh, bw, channels, nb, backend, int(canvas.nbytes)), t0)

    def _credit_batch(self, batch, wall: float, h2d_bytes: int,
                      d2h_bytes: int) -> None:
        """Split one retired batch's device wall across its members by
        pixel share and land each share in the member's ledger (when it
        carries one) AND in exactly one of the goodput/overhead spend
        counters — every second of measured batch wall is attributed
        once, which is what makes the conservation check in the
        acceptance tests solvable. Warm/prewarm submits (ledger
        ``kind != "request"``) are overhead; a ledger-less request
        (bare in-process serve) is goodput."""
        self._m_h2d_bytes.inc(int(h2d_bytes))
        self._m_d2h_bytes.inc(int(d2h_bytes))
        px = [max(1, int(np.prod(r.shape))) for r in batch]
        total = sum(px)
        goodput = overhead = 0.0
        for r, p in zip(batch, px):
            frac = p / total
            share = wall * frac
            led = r.ledger
            if led is not None:
                led.add_device(share, h2d_bytes=int(h2d_bytes * frac),
                               d2h_bytes=int(d2h_bytes * frac))
            if led is not None and led.kind != "request":
                overhead += share
            else:
                goodput += share
        if goodput > 0:
            self._m_goodput.inc(goodput)
        if overhead > 0:
            self._m_overhead.inc(overhead)

    def _retire(self, batch, out_dev, meta, t0) -> None:
        """Block on one in-flight batch, crop per-request outputs, resolve
        futures, record latency + achieved-bandwidth metrics."""
        with _obs_span("serve.drain", "serve", batch=len(batch),
                       trace_ids=_batch_trace_ids(batch)):
            if isinstance(meta, dict) and meta.get("sharded"):
                self._retire_sharded(batch, out_dev, meta, t0)
            else:
                self._retire_inner(batch, out_dev, meta, t0)

    def _retire_sharded(self, batch, outs, meta, t0) -> None:
        """Fence each sharded launch in dispatch order, crop the mesh
        pad off (``ShardedRunner.fetch``) and resolve futures — the
        sharded analog of the bucket retire. No HBM-roofline sample:
        the batch_hbm_gbps model is per-chip, and a spatially-sharded
        launch splits the frame across chips (the run CLI's
        ``--breakdown`` owns that roofline)."""
        runner = meta["runner"]
        if self._fault_d2h is not None:
            self._fault_d2h()
        results = [runner.fetch(o) for o in outs]  # blocks per launch
        t1 = time.perf_counter()
        self._m_batches.inc()
        self._m_blat.observe(t1 - t0)
        ph, pw = runner.padded_shape
        ch = batch[0].shape[2] if len(batch[0].shape) == 3 else 1
        self._credit_batch(
            batch, t1 - t0, len(batch) * ph * pw * ch,
            sum(int(np.asarray(o).nbytes) for o in results),
        )
        witness_queue = []
        for r, out in zip(batch, results):
            res = np.ascontiguousarray(out)
            if self._fault_corrupt_result is not None and _checksum.fired(
                    self._fault_corrupt_result, r.req_id):
                res = _checksum.corrupt_array(res)
            self._record_request_span(r, t1)
            if not r.future.done() and _resolve(r.future, res):
                self._m_completed.inc()
                self._m_rlat.observe(t1 - r.t_submit)
            if r.witness_src is not None:
                witness_queue.append((r, res))
        for r, res in witness_queue:
            self._witness_one(r, res)

    def _retire_inner(self, batch, out_dev, meta, t0) -> None:
        bh, bw, channels, nb, backend, h2d_bytes = meta
        if self._fault_d2h is not None:
            self._fault_d2h()
        out = np.asarray(out_dev)  # blocks until the device is done
        t1 = time.perf_counter()
        self._m_batches.inc()
        self._m_blat.observe(t1 - t0)
        self._credit_batch(batch, t1 - t0, h2d_bytes, int(out.nbytes))
        reps = batch[0].reps
        if reps > 0:
            from tpu_stencil.runtime import roofline

            # fuse=1: the bucket executable applies the (vmapped) step
            # once per rep — it never runs the fused-chunk kernel, so the
            # default-fuse traffic divisor would under-report achieved
            # bandwidth by DEFAULT_FUSE x whenever the backend resolves
            # to pallas.
            gbps, _pct = roofline.achieved_frames(
                bh * bw * channels, nb, (t1 - t0) / reps, backend,
                batch[0].filter_name, bh, fuse=1,
            )
            self._m_gbps.observe(gbps)
        witness_queue = []
        for i, r in enumerate(batch):
            h, w = r.shape[:2]  # image was consumed at dispatch
            res = out[i, :h, :w].copy()
            # Corrupt INSIDE the request's true pixels (the canvas
            # midpoint could land in the bucket pad, which the crop
            # would silently heal — defeating the chaos test).
            if self._fault_corrupt_result is not None and _checksum.fired(
                    self._fault_corrupt_result, r.req_id):
                res = _checksum.corrupt_array(res)
            self._record_request_span(r, t1)
            # A client may have cancelled its (still-pending) future; the
            # result is simply dropped — one cancellation must never
            # poison its batch-mates' results.
            if not r.future.done() and _resolve(r.future, res):
                self._m_completed.inc()
                self._m_rlat.observe(t1 - r.t_submit)
            if r.witness_src is not None:
                witness_queue.append((r, res))
        # Witness AFTER every future resolved: verification must never
        # stretch the batch-mates' latency tail. (The sampler picked at
        # dispatch — the input snapshot outlives the staging buffer.)
        for r, res in witness_queue:
            self._witness_one(r, res)

    def _record_request_span(self, r: Request, t1: float) -> None:
        """File the per-request ``serve.request`` record (submit →
        retire) with the request's OWN trace id — the worker thread has
        no bound context and a batch mixes traces, so the batch-scope
        spans cannot carry this. Recorded BEFORE the future resolves:
        a handler woken by the result may immediately dump the trace,
        and the record must already be in the ring. No-op when no span
        sink is installed (the disabled hot path)."""
        if r.trace_id and _obs_tracing.sinks_active():
            _obs_tracing.emit_span(
                "serve.request", "serve", r.t_submit, t1,
                trace_id=r.trace_id, span_id=r.span_id,
                req_id=r.req_id, reps=r.reps,
            )

    def _witness_one(self, r: Request, got: np.ndarray) -> None:
        """Re-execute one sampled request through the eager measured-
        equivalent program (:func:`integrity.witness.device_witness` —
        none of this engine's compiled artifacts) and compare bit-exact.
        The verdict is counted and handed to ``on_witness``; it never
        touches the request's (already resolved) future — witnessing is
        about the REPLICA, not the response. A witness that itself
        errors is no verdict at all: it must neither kill the worker
        nor count as evidence against the replica."""
        if r.reps > _witness_mod.WITNESS_MAX_REPS:
            return  # see WITNESS_MAX_REPS: verification must stay cheap
        t_w0 = time.perf_counter()
        try:
            with _obs_span("integrity.witness", "integrity",
                           req_id=r.req_id, reps=r.reps):
                want = _witness_mod.device_witness(
                    r.witness_src, r.filter_name, r.reps,
                    self.cfg.boundary,
                )
                ok = bool(np.array_equal(want, np.asarray(got)))
        except Exception:
            self.registry.counter("integrity_witness_errors_total").inc()
            return
        # Witness re-execution is paid-for device time that produced no
        # client byte: it lands in overhead, with its own sub-counter so
        # the conservation check can avoid double-counting
        # (witness ⊆ overhead).
        wit_s = time.perf_counter() - t_w0
        self._m_witness_s.inc(wit_s)
        self._m_overhead.inc(wit_s)
        self._m_witness_total.inc()
        if not ok:
            self._m_witness_bad.inc()
            # The black-box record of a silent-corruption catch: dump
            # the request's spans + emit the structured event (no-op
            # spool-wise unless a recorder is installed).
            _obs_flight.trigger(
                "witness_mismatch", trace_id=r.trace_id, tier="serve",
                req_id=r.req_id, reps=r.reps,
            )
        cb = self.on_witness
        if cb is not None:
            try:
                cb(ok)
            except Exception:
                pass  # a broken verdict sink must not crash the worker

    def _worker_loop(self) -> None:
        try:
            self._worker_loop_inner()
        except BaseException as e:
            # An unhandled escape from the loop — including
            # BaseException-level failures the per-batch catches
            # deliberately do not absorb — is a worker death. Without
            # propagation every pending/in-flight future would wait
            # forever: fail them all typed and reject future submits.
            self._on_worker_death(e)

    def _on_worker_death(self, exc: BaseException) -> None:
        with self._cond:
            self._crashed = exc
            victims = list(self._current_batch)
            self._current_batch = []
            victims.extend(self._pending)
            self._pending.clear()
            while self._inflight_batches:
                victims.extend(self._inflight_batches.popleft()[0])
            self._m_depth.set(0)
            self._m_inflight.set(0)
            self._cond.notify_all()
        self._m_crashes.inc()
        err = WorkerCrashed(
            f"serve worker thread died ({type(exc).__name__}: {exc})"
        )
        err.__cause__ = exc
        for r in victims:
            if not r.future.done() and _resolve(r.future, exc=err):
                self._m_failed.inc()

    def _worker_loop_inner(self) -> None:
        try:
            # On the worker thread, not in __init__: the availability
            # probe touches jax.local_devices(), and constructing a
            # server must never force backend init on the caller.
            self._memsampler.start()
        except Exception:
            pass  # telemetry must never take down the serving loop
        inflight = self._inflight_batches
        while True:
            with self._cond:
                while (not self._pending and not self._closing
                       and not inflight):
                    self._cond.wait()
                batch, expired = self._take_batch_locked()
                closing = self._closing
            for r in expired:
                # Typed, outside the lock: an expired request fails
                # instead of occupying a batch slot.
                self._m_deadline.inc()
                waited = time.perf_counter() - r.t_submit
                _obs_flight.trigger(
                    "deadline_exceeded", trace_id=r.trace_id,
                    tier="serve", duration_s=waited, req_id=r.req_id,
                )
                if not r.future.done() and _resolve(
                    r.future,
                    exc=DeadlineExceeded(
                        f"request {r.req_id} expired after waiting "
                        f"{waited:.3f}s"
                    ),
                ):
                    self._m_failed.inc()
            if batch:
                self._current_batch = batch  # death-handler visibility
                try:
                    inflight.append(self._dispatch(batch))
                    self._m_inflight.set(len(inflight))
                except Exception as e:  # resolve, don't kill the loop
                    for r in batch:
                        if not r.future.done() and _resolve(r.future, exc=e):
                            self._m_failed.inc()
                self._current_batch = []
            # Retire when the pipeline is full (keeps depth bounded) or
            # when there is nothing new to overlap with.
            while inflight and (
                len(inflight) >= self.cfg.pipeline_depth or not batch
            ):
                done_batch, out_dev, meta, t0 = inflight.popleft()
                self._current_batch = done_batch  # death-handler visibility
                try:
                    self._retire(done_batch, out_dev, meta, t0)
                except Exception as e:
                    for r in done_batch:
                        if not r.future.done() and _resolve(r.future, exc=e):
                            self._m_failed.inc()
                self._current_batch = []
                self._m_inflight.set(len(inflight))
                if batch:
                    break  # go assemble the next batch for overlap
            with self._lock:
                drained = not self._pending
            if closing and drained and not inflight and not batch:
                # Reject anything that raced in after the closing flag.
                with self._lock:
                    leftovers = list(self._pending)
                    self._pending.clear()
                for r in leftovers:
                    _resolve(r.future, exc=ServerClosed("server closed"))
                return


def get_last_server() -> Optional[StencilServer]:
    """The most recently constructed server, if still alive — backs the
    module-level :func:`tpu_stencil.serve.stats` convenience."""
    ref = _last_server_ref
    return ref() if ref is not None else None
