"""Synthetic load generator for the serving engine.

Two standard load models, so throughput AND tail latency are measurable
(closed loops hide queueing delay, open loops hide service capacity —
you need both):

* **closed-loop**: ``concurrency`` workers, each submit-and-wait; offered
  load self-throttles to service rate. Measures capacity (throughput at
  full pipe) and in-service latency.
* **open-loop**: submissions arrive at a fixed ``rate`` regardless of
  completions — the "millions of users" shape. Overload surfaces as
  :class:`~tpu_stencil.serve.engine.QueueFull` rejections (counted, never
  buffered), exercising the backpressure contract. ``rate_fps`` is the
  fixed-frame-rate spelling of the same loop (``--rate-fps``): the
  arrival law of a live video feed, reporting achieved vs requested
  frame rate — one loadgen drives stream and serve benchmarks alike.

The report pulls latency percentiles and rejection counts from the
server's metrics registry — the loadgen measures the server with the
server's own instruments, so the numbers in a report are the numbers an
operator would scrape in production.

Deterministic: shapes and pixels come from a seeded generator, so a run
is reproducible on CPU in tier-1 and on TPU via bench_sweep.
"""

from __future__ import annotations

import concurrent.futures
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu_stencil.integrity import checksum as _checksum
from tpu_stencil.obs import context as _obs_ctx
from tpu_stencil.serve.engine import (
    QueueFull,
    ServerClosed,
    StencilServer,
)

DEFAULT_SHAPES: Tuple[Tuple[int, int], ...] = ((48, 36), (64, 48), (30, 50))

#: --verify golden only checks frames up to this many true pixels: the
#: independent NumPy golden runs per-pixel Python loops, so it is a
#: *small-frame* referee (the default loadgen shapes all qualify);
#: larger frames silently skip golden verification (crc still covers
#: the wire).
GOLDEN_MAX_PIXELS = 1 << 12

VERIFY_MODES = (None, "crc", "golden")


def _verify_failure_counter():
    from tpu_stencil import obs

    return obs.registry().counter("integrity_verify_failures_total")


def _golden_for(image: np.ndarray, reps: int,
                filter_name: str) -> Optional[np.ndarray]:
    if image.shape[0] * image.shape[1] > GOLDEN_MAX_PIXELS:
        return None
    from tpu_stencil import filters
    from tpu_stencil.ops import stencil

    return stencil.reference_stencil_numpy(
        image, filters.get_filter(filter_name), reps
    )


class HttpTarget:
    """Duck-typed :class:`StencilServer` stand-in that drives the
    NETWORK tier (``python -m tpu_stencil net``) over ``POST /v1/blur``
    — the same closed/open loops, ``--rate-fps`` arrival law, and
    report schema measure a remote fleet instead of an in-process
    engine (``--http URL`` on the serve CLI).

    The status-code mapping inverts the frontend's: 429 (and a
    shedding 503) raise :class:`QueueFull` — transient backpressure
    the loops already know how to retry or shed, carrying the
    response's ``Retry-After`` hint as ``retry_after_s`` so the shared
    retry loop honors it as the backoff floor
    (``retry_after_honored_total`` in the report) — a draining 503
    raises :class:`ServerClosed` (permanent for that process: the
    drain gate never reopens, so re-offering is futile), and 504
    raises a typed ``DeadlineExceeded``. ``stats()`` scrapes
    ``/statusz`` and returns the tier's net-registry snapshot, whose
    ``rejected_total`` counter and ``request_latency_seconds``
    histogram are exactly the keys the report reads — so an HTTP
    report, like an in-process one, shows what an operator would
    scrape, not client-side guesses."""

    def __init__(self, url: str, max_workers: int = 32,
                 timeout_s: float = 300.0,
                 verify: Optional[str] = None,
                 tenant: Optional[str] = None) -> None:
        if verify not in VERIFY_MODES:
            raise ValueError(
                f"verify must be one of {VERIFY_MODES}, got {verify!r}"
            )
        self.url = url.rstrip("/")
        self._timeout = timeout_s
        # --tenant: stamp X-Tenant on every request so the tier's cost
        # ledger meters this run under one name; every 200's X-Cost-*
        # headers roll up into cost_snapshot() (the report's "cost"
        # key) — metering is drivable and assertable from the client.
        self.tenant = tenant
        self._cost_lock = threading.Lock()
        self._cost: Dict = {
            "responses": 0, "device_us": 0, "queue_us": 0,
            "by_source": {},
        }
        # --verify (docs/RESILIENCE.md "Integrity model"): any non-None
        # mode stamps each request with X-Content-Crc32c (exercising
        # the tier's ingest validation); "crc" additionally checks each
        # 200 body against its X-Result-Crc32c stamp — a mismatch (or a
        # missing stamp) counts integrity_verify_failures_total and
        # raises typed. "golden" is checked in run() (it needs the
        # request's pixels, which outlive this target).
        self.verify = verify
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix="tpu-stencil-httpgen",
        )

    def _post(self, image: np.ndarray, reps: int,
              filter_name: Optional[str],
              deadline_s: Optional[float]) -> np.ndarray:
        import urllib.error
        import urllib.request

        from tpu_stencil.resilience.errors import DeadlineExceeded

        h, w = image.shape[:2]
        channels = image.shape[2] if image.ndim == 3 else 1
        payload = image.tobytes()
        # The CLIENT is the outermost tracing edge here: the bound
        # context (loadgen's per-request mint, or an embedder's) rides
        # the wire, so every hop of this request — and its flight-
        # recorder dump, if an anomaly fires — greps to one id.
        ctx = _obs_ctx.current() or _obs_ctx.fresh()
        headers = {
            "X-Width": str(w), "X-Height": str(h),
            "X-Reps": str(reps), "X-Channels": str(channels),
            "Content-Type": "application/octet-stream",
            **_obs_ctx.headers_for(ctx),
        }
        if self.verify is not None:
            headers[_checksum.CRC_HEADER] = str(_checksum.crc32c(payload))
        if self.tenant:
            headers["X-Tenant"] = self.tenant
        if filter_name:
            headers["X-Filter"] = filter_name
        if deadline_s:
            headers["X-Request-Timeout"] = repr(float(deadline_s))
        req = urllib.request.Request(
            self.url + "/v1/blur", data=payload,
            headers=headers, method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as r:
                body = r.read()
                self._tally_cost(r.headers)
                if self.verify == "crc":
                    stamp = r.headers.get(_checksum.RESULT_HEADER)
                    # stamp_matches treats a missing OR malformed stamp
                    # as a failure (wire corruption hits header bytes
                    # as easily as the body) — counted, then typed.
                    if not _checksum.stamp_matches(stamp, body):
                        _verify_failure_counter().inc()
                        raise _checksum.ChecksumMismatch(
                            f"loadgen --verify crc (stamp {stamp!r})",
                            -1, _checksum.crc32c(body),
                        )
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace").strip()
            if e.code == 503 and "draining" in detail:
                # The drain gate is one-way for that process: re-offering
                # is futile, unlike a shed 503 that clears with the
                # backlog. ServerClosed classifies PERMANENT — fail fast,
                # same as the in-process spelling.
                raise ServerClosed(f"HTTP 503: {detail}") from None
            if e.code in (429, 503):
                exc = QueueFull(f"HTTP {e.code}: {detail}")
                # The shed/queue-full responses carry a Retry-After
                # hint; attach it so the shared retry loop honors it
                # as the backoff FLOOR (retry_call) instead of pure
                # exp-jitter — re-offering sooner than the server
                # asked just burns its admission path.
                ra = e.headers.get("Retry-After")
                if ra:
                    try:
                        exc.retry_after_s = float(ra)
                    except ValueError:
                        pass  # an unparseable hint is no hint
                raise exc from None
            if e.code == 504:
                raise DeadlineExceeded(f"HTTP 504: {detail}") from None
            # Anything else (400/404/413/500...) is deterministic: the
            # same request fails the same way, so raise the type the
            # retry classifier treats as PERMANENT — the closed loop
            # must fail fast, not re-offer for the give-up budget.
            raise ValueError(f"HTTP {e.code}: {detail}") from None
        return np.frombuffer(body, np.uint8).reshape(image.shape)

    def submit(self, image: np.ndarray, reps: int,
               filter_name: Optional[str] = None,
               deadline_s: Optional[float] = None,
               ) -> "concurrent.futures.Future":
        """Async POST. Unlike the in-process engine, backpressure
        cannot raise synchronously (the 429 arrives with the response),
        so :class:`QueueFull` surfaces from ``future.result()`` — the
        open loop treats both spellings as a shed."""
        img = np.array(image, copy=True)  # same buffer-reuse contract
        # Contextvars do not cross into the pool thread: capture the
        # caller's trace context here and re-bind it around the POST.
        ctx = _obs_ctx.current()

        def task() -> np.ndarray:
            with _obs_ctx.bind(ctx):
                return self._post(img, reps, filter_name, deadline_s)

        return self._pool.submit(task)

    def submit_retrying(self, image: np.ndarray, reps: int,
                        filter_name: Optional[str] = None,
                        deadline_s: Optional[float] = None,
                        policy=None,
                        give_up_after_s: Optional[float] = 300.0,
                        ) -> "concurrent.futures.Future":
        """:meth:`submit` re-offering on backpressure under the shared
        resilience retry policy — the closed-loop client shape, same
        contract as :meth:`StencilServer.submit_retrying` (same
        ``reoffer_call`` scaffolding; only the delays differ — an HTTP
        round-trip per offer deserves a longer backoff)."""
        from tpu_stencil.resilience import retry as _retry

        img = np.array(image, copy=True)
        ctx = _obs_ctx.current()  # re-bound on the pool thread

        def task() -> np.ndarray:
            with _obs_ctx.bind(ctx):
                return _retry.reoffer_call(
                    lambda: self._post(img, reps, filter_name,
                                       deadline_s),
                    policy=policy, give_up_after_s=give_up_after_s,
                    base_delay=0.005, max_delay=0.1,
                    label="net.submit",
                )

        return self._pool.submit(task)

    def _tally_cost(self, rh) -> None:
        """Roll one 200's X-Cost-* headers into the run's cost tally
        (absent headers — an older tier — tally nothing)."""
        dev = rh.get("X-Cost-Device-Us")
        if dev is None:
            return
        try:
            d = int(dev)
            q = int(rh.get("X-Cost-Queue-Us") or 0)
        except ValueError:
            return  # a malformed header is no measurement
        src = rh.get("X-Cost-Source") or "unknown"
        with self._cost_lock:
            c = self._cost
            c["responses"] += 1
            c["device_us"] += d
            c["queue_us"] += q
            c["by_source"][src] = c["by_source"].get(src, 0) + 1

    def cost_snapshot(self) -> Dict:
        """The per-tenant cost rollup for the report: what this run's
        responses said they cost, in the server's own X-Cost-*
        vocabulary."""
        with self._cost_lock:
            return {
                "tenant": self.tenant or "anon",
                "responses": self._cost["responses"],
                "device_us": self._cost["device_us"],
                "device_seconds": self._cost["device_us"] / 1e6,
                "queue_us": self._cost["queue_us"],
                "by_source": dict(self._cost["by_source"]),
            }

    def stats(self) -> dict:
        """The tier's net-registry snapshot, scraped from /statusz."""
        import json as _json
        import urllib.request

        with urllib.request.urlopen(self.url + "/statusz",
                                    timeout=self._timeout) as r:
            return _json.loads(r.read())["net"]

    def close(self) -> None:
        self._pool.shutdown(wait=False)


def synth_requests(
    n: int, shapes: Sequence[Tuple[int, int]], channels: Sequence[int],
    seed: int, group: int = 1,
) -> List[np.ndarray]:
    """n seeded random uint8 images cycling over shapes x channels.
    ``group`` > 1 cycles per GROUP of that many consecutive requests
    instead of per request — the bursty arrival mode's guarantee that
    every request of one tick shares a shape (and so a coalescing
    compatibility key); pixels stay distinct per request."""
    rng = np.random.default_rng(seed)
    group = max(1, int(group))
    out = []
    for i in range(n):
        h, w = shapes[(i // group) % len(shapes)]
        ch = channels[(i // group) % len(channels)]
        shape = (h, w) if ch == 1 else (h, w, ch)
        out.append(rng.integers(0, 256, size=shape, dtype=np.uint8))
    return out


def zipf_requests(
    n: int, shapes: Sequence[Tuple[int, int]], channels: Sequence[int],
    seed: int, s: float, keys: int = 16,
) -> Tuple[List[np.ndarray], List[int]]:
    """``n`` requests drawn from a seeded pool of ``keys`` DISTINCT
    frames under a Zipf(``s``) popularity law — the repeat-heavy
    keyspace the result cache (``--result-cache-mb``) exists for. Key
    rank ``k`` (1-based) is drawn with probability ``k^-s / H``: at
    ``s=0`` every key is uniform (worst case for a cache), at ``s≈1``
    a handful of keys dominate (the web-traffic shape).

    The draw is a normalized power-law ``rng.choice`` over the finite
    pool, NOT ``numpy.random.zipf`` (which is unbounded and whose
    support would leak keys past the pool) — and it is fully seeded:
    the same ``(n, shapes, channels, seed, s, keys)`` replays the
    identical request sequence byte-for-byte, so a cache-hit-ratio
    measurement is reproducible.

    Returns ``(images, key_indices)``: the per-request frames (entries
    are shared references into the pool — callers copy on submit) and
    the drawn pool index per request, for hit-ratio accounting."""
    if not s >= 0:
        raise ValueError(f"zipf exponent must be >= 0, got {s!r}")
    if keys < 1:
        raise ValueError(f"zipf pool needs >= 1 key, got {keys}")
    pool = synth_requests(keys, shapes, channels, seed)
    ranks = np.arange(1, keys + 1, dtype=np.float64)
    weights = ranks ** -float(s)
    weights /= weights.sum()
    # A distinct stream from the pool's pixels: reseeding with the
    # same constant everywhere keeps the draw independent of pool
    # size (growing `keys` must not reshuffle which request slots
    # repeat).
    drng = np.random.default_rng(seed ^ 0x21BF)
    idx = drng.choice(keys, size=n, p=weights)
    return [pool[j] for j in idx], [int(j) for j in idx]


def run(
    server: StencilServer,
    mode: str = "closed",
    requests: int = 64,
    concurrency: int = 4,
    rate: float = 200.0,
    reps: int = 5,
    shapes: Sequence[Tuple[int, int]] = DEFAULT_SHAPES,
    channels: Sequence[int] = (3,),
    seed: int = 0,
    timeout: float = 300.0,
    rate_fps: Optional[float] = None,
    verify: Optional[str] = None,
    verify_filter: str = "gaussian",
    per_request: bool = False,
    burst: int = 1,
    zipf: Optional[float] = None,
    zipf_keys: int = 16,
    ramp: Optional[Tuple[float, float, float]] = None,
    ramp_phases: int = 4,
) -> Dict:
    """Drive ``server`` with synthetic load; return the report dict.

    ``server`` is a :class:`StencilServer` or any duck-typed stand-in
    with ``submit``/``submit_retrying``/``stats`` — in particular
    :class:`HttpTarget`, which points the same loops at the network
    tier (``--http URL``) with the same report schema.

    Report keys: ``mode``, ``requests``, ``completed``, ``rejected``,
    ``wall_seconds``, ``throughput_rps``, ``p50_s``, ``p99_s`` (request
    latency from the registry), plus the full ``stats`` snapshot.

    ``verify`` (``--verify {crc,golden}``, docs/RESILIENCE.md
    "Integrity model"): every request is stamped with its
    ``X-Content-Crc32c`` (HTTP targets), and each completed response is
    checked — ``crc`` against the tier's ``X-Result-Crc32c`` stamp
    (inside :class:`HttpTarget`), ``golden`` against the independent
    NumPy golden for frames up to :data:`GOLDEN_MAX_PIXELS`. Failures
    count ``verify_failures_total`` in the report; closed loops fail
    fast on the first one (zero tolerance), open loops count and keep
    offering.

    Every request is minted its own ``X-Trace-Id``
    (:mod:`tpu_stencil.obs.context` — loadgen is the outermost tracing
    edge), so the report names the SLOWEST request's trace id
    (``slowest_trace_id`` / ``slowest_latency_s``): a p99 straggler
    greps straight to its ``/debug/trace`` tree and flight-recorder
    dump. ``per_request=True`` additionally returns one
    ``{i, trace_id, latency_s, ok}`` record per completed request
    (the ``--per-request`` CLI table).

    ``rate_fps``: the open-loop fixed-frame-rate mode (``--rate-fps``)
    — one frame is *due* every ``1/rate_fps`` seconds regardless of
    completions, the arrival law of a live video feed, so the same
    loadgen drives stream benchmarks and serve benchmarks. Forces
    ``mode='open'`` at that rate and adds ``requested_fps`` /
    ``offered_fps`` (submissions over the offered window, rejects
    included) / ``achieved_fps`` (completions over the wall) to the
    report — achieved < requested means the pipe, not the source, is
    the bottleneck.

    ``burst`` (``--burst N``): the bursty open-loop arrival mode — N
    simultaneous SAME-shape requests per tick (distinct payloads), tick
    gaps drawn from a seeded exponential (a Poisson arrival process at
    the same mean rate) instead of a metronome. This is the client-side
    shape that actually exercises cross-request coalescing at the
    network edge: a metronome at modest rates never offers two
    compatible requests inside one window. The report's p50/p99 sit
    next to achieved fps as always. ``burst=1`` (default) is exactly
    the pre-existing fixed-period open loop; burst > 1 requires an open
    loop (``mode='open'`` or ``rate_fps``).

    ``zipf`` (``--zipf S``): draw the request stream from a seeded pool
    of ``zipf_keys`` distinct frames under a Zipf(S) popularity law
    (:func:`zipf_requests`) instead of all-distinct frames — the
    repeat-heavy keyspace the network tier's result cache serves. The
    report gains ``zipf`` / ``zipf_keys`` / ``distinct_keys_offered``
    and ``cache_hit_ratio`` (``result_cache_hits_total`` over hits +
    misses from the target's own registry; ``None`` when the target
    has no result cache). Deterministic: the same seed replays the
    identical key sequence.

    ``ramp`` (``--ramp START_FPS:END_FPS:SECONDS``): the ramped
    open-loop profile — the total window is split into ``ramp_phases``
    equal phases, each a metronome at a frame rate stepped linearly
    from START to END, arrivals due on schedule regardless of
    completions (the same non-negotiable arrival law as ``rate_fps``,
    swept instead of held).  Forces ``mode='open'`` and overrides
    ``requests`` with the schedule's own count (≈ mean fps × seconds);
    the report gains ``ramp.phases`` — one ``{fps, seconds, requests,
    completed, achieved_fps, p99_s}`` row per phase, achieved fps and
    nearest-rank p99 both from the client-side per-request records so
    a resize mid-ramp shows up in exactly the phase it happened.
    Seeded like every other profile: the same ``(ramp, seed, shapes,
    channels)`` replays the identical request stream.  Mutually
    exclusive with ``rate_fps`` and ``burst > 1``.
    """
    ramp_plan: Optional[List[Tuple[float, float, int]]] = None
    if ramp is not None:
        start_fps, end_fps, ramp_secs = (float(v) for v in ramp)
        if not (start_fps > 0 and end_fps > 0 and ramp_secs > 0):
            raise ValueError(
                f"ramp needs positive START_FPS, END_FPS and SECONDS, "
                f"got {ramp!r}"
            )
        if rate_fps is not None:
            raise ValueError("ramp and rate_fps are exclusive arrival "
                             "laws (ramp sweeps the rate)")
        if burst > 1:
            raise ValueError("ramp is a metronome profile; burst > 1 "
                             "is not supported with it")
        if ramp_phases < 1:
            raise ValueError(
                f"ramp_phases must be >= 1, got {ramp_phases}"
            )
        mode = "open"
        nphase = int(ramp_phases)
        ramp_plan = []
        for p in range(nphase):
            frac = p / (nphase - 1) if nphase > 1 else 0.0
            fps_p = start_fps + (end_fps - start_fps) * frac
            dur_p = ramp_secs / nphase
            ramp_plan.append((fps_p, dur_p,
                              max(1, int(round(fps_p * dur_p)))))
        requests = sum(n for _, _, n in ramp_plan)
    if rate_fps is not None:
        if not rate_fps > 0:
            raise ValueError(f"rate_fps must be > 0, got {rate_fps!r}")
        mode, rate = "open", float(rate_fps)
    if burst < 1:
        raise ValueError(f"burst must be >= 1, got {burst}")
    if burst > 1 and mode != "open":
        raise ValueError(
            "burst is an open-loop arrival mode (use mode='open' or "
            "rate_fps)"
        )
    if mode not in ("closed", "open"):
        raise ValueError(f"mode must be closed|open, got {mode!r}")
    if verify not in VERIFY_MODES:
        raise ValueError(
            f"verify must be one of {VERIFY_MODES}, got {verify!r}"
        )
    from tpu_stencil import obs

    # Client-side counter delta: how many re-offers this run slept to
    # a server-provided Retry-After floor (retry_call honors the hint
    # the shed 503 / queue-full 429 responses carry).
    honored0 = obs.registry().counter(
        "resilience_retry_after_honored_total"
    ).value
    zipf_idx: Optional[List[int]] = None
    if zipf is not None:
        images, zipf_idx = zipf_requests(requests, shapes, channels,
                                         seed, zipf, zipf_keys)
    else:
        images = synth_requests(requests, shapes, channels, seed,
                                group=burst)
    completed = 0
    completed_lock = threading.Lock()
    # Per-request trace records ({i, trace_id, latency_s, ok}), always
    # collected (bounded by `requests`): the report names the slowest
    # trace even when the caller skipped the per-request table.
    records: List[Dict] = []
    records_lock = threading.Lock()

    def _record(i: int, trace_id: str, latency_s: float,
                ok: bool) -> None:
        with records_lock:
            records.append({"i": i, "trace_id": trace_id,
                            "latency_s": latency_s, "ok": ok})
    verify0 = _verify_failure_counter().value
    goldens: Dict[int, Optional[np.ndarray]] = {}
    goldens_lock = threading.Lock()

    def _check_golden(i: int, got) -> bool:
        """--verify golden: compare a completed result against the
        independent NumPy golden (memoized per request index; frames
        past GOLDEN_MAX_PIXELS skip). Returns False + counts on a
        mismatch."""
        if verify != "golden":
            return True
        # Zipf streams repeat pool keys: memoize the golden per POOL
        # key, not per request slot — K computations, not N.
        gi = zipf_idx[i] if zipf_idx is not None else i
        with goldens_lock:
            if gi not in goldens:
                goldens[gi] = _golden_for(images[i], reps,
                                          verify_filter)
            want = goldens[gi]
        if want is None or np.array_equal(np.asarray(got), want):
            return True
        _verify_failure_counter().inc()
        return False

    t_start = time.perf_counter()

    if mode == "closed":
        next_i = [0]
        errors: List[BaseException] = []

        def worker():
            nonlocal completed
            while True:
                with completed_lock:
                    if errors:
                        return  # a sibling failed; stop offering load
                    i = next_i[0]
                    if i >= requests:
                        return
                    next_i[0] = i + 1
                try:
                    # Closed loops retry backpressure (the client is
                    # synchronous): the shared resilience.retry policy
                    # classifies QueueFull transient and backs off with
                    # jitter, but never past the run deadline — a wedged
                    # server must not spin these workers forever while
                    # run() returns a plausible-looking partial report.
                    ctx = _obs_ctx.fresh()
                    t_req = time.perf_counter()
                    with _obs_ctx.bind(ctx):
                        fut = server.submit_retrying(
                            images[i], reps,
                            give_up_after_s=max(
                                0.001,
                                t_start + timeout - time.perf_counter()
                            ),
                        )
                    got = fut.result(timeout=timeout)
                    _record(i, ctx.trace_id,
                            time.perf_counter() - t_req, True)
                    if not _check_golden(i, got):
                        # Zero tolerance in the closed loop: one wrong
                        # result fails the run typed.
                        raise _checksum.WitnessMismatch(
                            f"loadgen --verify golden (request {i})"
                        )
                except BaseException as e:  # propagate via run(), never die silently
                    with completed_lock:
                        errors.append(e)
                    return
                with completed_lock:
                    completed += 1

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(max(1, concurrency))
        ]
        for t in threads:
            t.start()
        # One shared deadline across all joins — not timeout per thread.
        deadline = t_start + timeout
        for t in threads:
            t.join(max(0.0, deadline - time.perf_counter()))
        if errors:
            raise errors[0]
    else:  # open loop
        period = 1.0 / rate if rate > 0 else 0.0
        futures = []
        offered = 0

        def _offer(i: int) -> None:
            nonlocal offered
            offered += 1
            try:
                # The request index rides with the future: a shed
                # submission must not shift later results onto the
                # wrong golden.
                ctx = _obs_ctx.fresh()
                t_req = time.perf_counter()
                with _obs_ctx.bind(ctx):
                    f = server.submit(images[i], reps)
                f.add_done_callback(
                    # Completion time captured AT completion (the
                    # drain loop below reads results in submission
                    # order, so its clock would inflate latencies).
                    lambda fut, i=i, c=ctx, t=t_req: _record(
                        i, c.trace_id, time.perf_counter() - t,
                        fut.cancelled() is False
                        and fut.exception() is None,
                    )
                )
                futures.append((i, f))
            except QueueFull:
                pass  # counted by the server; open loops shed, not wait

        if ramp_plan is not None:
            # Ramp profile: each phase is its own metronome at the
            # stepped rate, due times anchored to the PHASE start so a
            # slow server never compresses the later (faster) phases.
            phase_bounds: List[Tuple[int, int]] = []
            phase_walls: List[float] = []
            i = 0
            for fps_p, _dur_p, n_p in ramp_plan:
                t_phase = time.perf_counter()
                period_p = 1.0 / fps_p
                for k in range(n_p):
                    delay = t_phase + k * period_p - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    _offer(i)
                    i += 1
                phase_walls.append(time.perf_counter() - t_phase)
                phase_bounds.append((i - n_p, i))
        else:
            # Bursty mode: ticks of `burst` back-to-back submissions,
            # the NEXT tick due an exponentially distributed gap later
            # (seeded: a run replays exactly). The mean inter-REQUEST
            # period is unchanged — a tick of N requests earns an
            # N-period mean gap — so `rate` keeps meaning
            # requests/second across modes.
            jrng = (np.random.default_rng(seed ^ 0xB5457)
                    if burst > 1 else None)
            t_due = t_start
            for i in range(requests):
                if i % burst == 0:
                    if i > 0:
                        t_due += (
                            jrng.exponential(period * burst)
                            if jrng is not None else period * burst
                        )
                    delay = t_due - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                _offer(i)
        offer_wall = time.perf_counter() - t_start
        deadline = time.perf_counter() + timeout
        shed_in_flight = 0
        for i, f in futures:
            try:
                got = f.result(
                    timeout=max(0.0, deadline - time.perf_counter())
                )
                _check_golden(i, got)  # open loop: count, keep draining
            except _checksum.ChecksumMismatch:
                # HttpTarget's --verify crc failure, already counted:
                # the open loop measures corruption, it does not abort.
                pass
            except (QueueFull, ServerClosed):
                # The HTTP target's backpressure arrives WITH the
                # response (a 429/503 resolved into the future), not
                # synchronously at submit — same shed, later spelling.
                # ServerClosed is the draining tier's 503: the open
                # loop measures the rejection, it does not abort.
                # In-process futures never resolve to either, so this
                # is a no-op for the classic path.
                shed_in_flight += 1
        completed = len(futures) - shed_in_flight

    wall = time.perf_counter() - t_start
    stats = server.stats()
    # Absent only against a tier that served zero requests (every one
    # shed/rejected): report zeros, don't crash the overload report.
    rlat = stats["histograms"].get(
        "request_latency_seconds", {"p50": 0.0, "p99": 0.0}
    )
    report = {
        "mode": mode,
        "requests": requests,
        "completed": completed,
        "rejected": stats["counters"]["rejected_total"],
        "wall_seconds": wall,
        "throughput_rps": completed / wall if wall > 0 else 0.0,
        "p50_s": rlat["p50"],
        "p99_s": rlat["p99"],
        "retry_after_honored_total": obs.registry().counter(
            "resilience_retry_after_honored_total"
        ).value - honored0,
        "stats": stats,
    }
    with records_lock:
        done_recs = sorted(records, key=lambda r: r["i"])
    ok_recs = [r for r in done_recs if r["ok"]] or done_recs
    if ok_recs:
        # Name the straggler: its trace id greps straight to the
        # /debug/trace tree and any flight-recorder dump it triggered.
        slowest = max(ok_recs, key=lambda r: r["latency_s"])
        report["slowest_trace_id"] = slowest["trace_id"]
        report["slowest_latency_s"] = slowest["latency_s"]
    if burst > 1:
        report["burst"] = burst
    if zipf is not None:
        report["zipf"] = float(zipf)
        report["zipf_keys"] = int(zipf_keys)
        report["distinct_keys_offered"] = len(set(zipf_idx))
        # Hit ratio from the TARGET's own instruments, like every
        # other report number — None means the target runs no result
        # cache (the counters don't exist in its registry).
        hits = stats["counters"].get("result_cache_hits_total")
        misses = stats["counters"].get("result_cache_misses_total")
        if hits is None or misses is None:
            report["cache_hit_ratio"] = None
        else:
            total = hits + misses
            report["cache_hit_ratio"] = (
                hits / total if total > 0 else 0.0
            )
    cost_fn = getattr(server, "cost_snapshot", None)
    if cost_fn is not None:
        # HTTP targets: the run's cost rollup from the tier's X-Cost-*
        # response headers, keyed by the stamped tenant.
        report["cost"] = cost_fn()
    if per_request:
        report["per_request"] = done_recs
    if verify is not None:
        report["verify"] = verify
        report["verify_failures_total"] = (
            _verify_failure_counter().value - verify0
        )
    if rate_fps is not None:
        # Achieved-vs-requested: offered over the submission window
        # (could the source keep its schedule?) and achieved over the
        # whole wall (did the pipe keep up, drain included?). The n
        # offers span (n-1) inter-arrival periods, so the window gets
        # one period added back — n offers over a bare (n-1)-period
        # wall would read ~n/(n-1) above requested on perfect pacing.
        report["requested_fps"] = float(rate_fps)
        # (Bursty runs: the n offers span n/burst ticks, so one whole
        # tick gap is added back — same reasoning, coarser grain.)
        offer_window = offer_wall + period * burst
        report["offered_fps"] = (
            offered / offer_window if offer_window > 0 else 0.0
        )
        report["achieved_fps"] = completed / wall if wall > 0 else 0.0
    if ramp_plan is not None:
        # Per-phase achieved fps + p99 from the CLIENT-side records —
        # the phase a completion belongs to is the phase its request
        # was offered in, so a mid-ramp resize (the elastic acceptance
        # run) shows its cost in exactly the right row.
        phases_rep = []
        for (fps_p, dur_p, n_p), (lo, hi), wall_p in zip(
            ramp_plan, phase_bounds, phase_walls
        ):
            lats = sorted(
                r["latency_s"] for r in done_recs
                if lo <= r["i"] < hi and r["ok"]
            )
            p99 = (lats[max(0, math.ceil(0.99 * len(lats)) - 1)]
                   if lats else 0.0)
            phases_rep.append({
                "fps": fps_p, "seconds": dur_p, "requests": n_p,
                "completed": len(lats),
                "achieved_fps": len(lats) / wall_p if wall_p > 0
                else 0.0,
                "p99_s": p99,
            })
        report["ramp"] = {
            "start_fps": float(ramp[0]), "end_fps": float(ramp[1]),
            "seconds": float(ramp[2]), "phases": phases_rep,
        }
    return report
