"""Synthetic load generator for the serving engine.

Two standard load models, so throughput AND tail latency are measurable
(closed loops hide queueing delay, open loops hide service capacity —
you need both):

* **closed-loop**: ``concurrency`` workers, each submit-and-wait; offered
  load self-throttles to service rate. Measures capacity (throughput at
  full pipe) and in-service latency.
* **open-loop**: submissions arrive at a fixed ``rate`` regardless of
  completions — the "millions of users" shape. Overload surfaces as
  :class:`~tpu_stencil.serve.engine.QueueFull` rejections (counted, never
  buffered), exercising the backpressure contract. ``rate_fps`` is the
  fixed-frame-rate spelling of the same loop (``--rate-fps``): the
  arrival law of a live video feed, reporting achieved vs requested
  frame rate — one loadgen drives stream and serve benchmarks alike.

The report pulls latency percentiles and rejection counts from the
server's metrics registry — the loadgen measures the server with the
server's own instruments, so the numbers in a report are the numbers an
operator would scrape in production.

Deterministic: shapes and pixels come from a seeded generator, so a run
is reproducible on CPU in tier-1 and on TPU via bench_sweep.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu_stencil.serve.engine import QueueFull, StencilServer

DEFAULT_SHAPES: Tuple[Tuple[int, int], ...] = ((48, 36), (64, 48), (30, 50))


def synth_requests(
    n: int, shapes: Sequence[Tuple[int, int]], channels: Sequence[int],
    seed: int,
) -> List[np.ndarray]:
    """n seeded random uint8 images cycling over shapes x channels."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        h, w = shapes[i % len(shapes)]
        ch = channels[i % len(channels)]
        shape = (h, w) if ch == 1 else (h, w, ch)
        out.append(rng.integers(0, 256, size=shape, dtype=np.uint8))
    return out


def run(
    server: StencilServer,
    mode: str = "closed",
    requests: int = 64,
    concurrency: int = 4,
    rate: float = 200.0,
    reps: int = 5,
    shapes: Sequence[Tuple[int, int]] = DEFAULT_SHAPES,
    channels: Sequence[int] = (3,),
    seed: int = 0,
    timeout: float = 300.0,
    rate_fps: Optional[float] = None,
) -> Dict:
    """Drive ``server`` with synthetic load; return the report dict.

    Report keys: ``mode``, ``requests``, ``completed``, ``rejected``,
    ``wall_seconds``, ``throughput_rps``, ``p50_s``, ``p99_s`` (request
    latency from the registry), plus the full ``stats`` snapshot.

    ``rate_fps``: the open-loop fixed-frame-rate mode (``--rate-fps``)
    — one frame is *due* every ``1/rate_fps`` seconds regardless of
    completions, the arrival law of a live video feed, so the same
    loadgen drives stream benchmarks and serve benchmarks. Forces
    ``mode='open'`` at that rate and adds ``requested_fps`` /
    ``offered_fps`` (submissions over the offered window, rejects
    included) / ``achieved_fps`` (completions over the wall) to the
    report — achieved < requested means the pipe, not the source, is
    the bottleneck.
    """
    if rate_fps is not None:
        if not rate_fps > 0:
            raise ValueError(f"rate_fps must be > 0, got {rate_fps!r}")
        mode, rate = "open", float(rate_fps)
    if mode not in ("closed", "open"):
        raise ValueError(f"mode must be closed|open, got {mode!r}")
    images = synth_requests(requests, shapes, channels, seed)
    completed = 0
    completed_lock = threading.Lock()
    t_start = time.perf_counter()

    if mode == "closed":
        next_i = [0]
        errors: List[BaseException] = []

        def worker():
            nonlocal completed
            while True:
                with completed_lock:
                    if errors:
                        return  # a sibling failed; stop offering load
                    i = next_i[0]
                    if i >= requests:
                        return
                    next_i[0] = i + 1
                try:
                    # Closed loops retry backpressure (the client is
                    # synchronous): the shared resilience.retry policy
                    # classifies QueueFull transient and backs off with
                    # jitter, but never past the run deadline — a wedged
                    # server must not spin these workers forever while
                    # run() returns a plausible-looking partial report.
                    fut = server.submit_retrying(
                        images[i], reps,
                        give_up_after_s=max(
                            0.001, t_start + timeout - time.perf_counter()
                        ),
                    )
                    fut.result(timeout=timeout)
                except BaseException as e:  # propagate via run(), never die silently
                    with completed_lock:
                        errors.append(e)
                    return
                with completed_lock:
                    completed += 1

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(max(1, concurrency))
        ]
        for t in threads:
            t.start()
        # One shared deadline across all joins — not timeout per thread.
        deadline = t_start + timeout
        for t in threads:
            t.join(max(0.0, deadline - time.perf_counter()))
        if errors:
            raise errors[0]
    else:  # open loop
        period = 1.0 / rate if rate > 0 else 0.0
        futures = []
        offered = 0
        for i in range(requests):
            t_due = t_start + i * period
            delay = t_due - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            offered += 1
            try:
                futures.append(server.submit(images[i], reps))
            except QueueFull:
                pass  # counted by the server; open loops shed, not wait
        offer_wall = time.perf_counter() - t_start
        deadline = time.perf_counter() + timeout
        for f in futures:
            f.result(timeout=max(0.0, deadline - time.perf_counter()))
        completed = len(futures)

    wall = time.perf_counter() - t_start
    stats = server.stats()
    rlat = stats["histograms"]["request_latency_seconds"]
    report = {
        "mode": mode,
        "requests": requests,
        "completed": completed,
        "rejected": stats["counters"]["rejected_total"],
        "wall_seconds": wall,
        "throughput_rps": completed / wall if wall > 0 else 0.0,
        "p50_s": rlat["p50"],
        "p99_s": rlat["p99"],
        "stats": stats,
    }
    if rate_fps is not None:
        # Achieved-vs-requested: offered over the submission window
        # (could the source keep its schedule?) and achieved over the
        # whole wall (did the pipe keep up, drain included?). The n
        # offers span (n-1) inter-arrival periods, so the window gets
        # one period added back — n offers over a bare (n-1)-period
        # wall would read ~n/(n-1) above requested on perfect pacing.
        report["requested_fps"] = float(rate_fps)
        offer_window = offer_wall + period
        report["offered_fps"] = (
            offered / offer_window if offer_window > 0 else 0.0
        )
        report["achieved_fps"] = completed / wall if wall > 0 else 0.0
    return report
