"""Thread-safe metrics registry for the serving engine.

The observability layer the reference program never needed (one process,
one image, one timer): a serving loop fields a stream of heterogeneous
requests, so the interesting numbers are *distributions* (queue wait,
batch latency) and *rates* (requests, rejections, padded-pixel waste),
not a single wall-clock. Everything is in-process and dependency-free:
``Registry.snapshot()`` returns a plain dict (the ``serve.stats()`` /
``--stats-json`` schema, documented in docs/SERVING.md).

Histograms keep a bounded deterministic reservoir: past ``cap``
observations each new sample evicts a pseudo-randomly chosen slot
(seeded ``random.Random``), so percentile queries stay O(cap log cap)
and memory stays bounded no matter how long the server runs — the same
never-unbounded discipline as the request queue.

Every histogram also maintains fixed cumulative buckets
(:data:`DEFAULT_BUCKETS`, ``le``-keyed like OpenMetrics) next to the
reservoir: bucket counts subtract cleanly between two snapshots, so the
time-series sampler (:mod:`tpu_stencil.obs.timeseries`) can compute
*windowed* tail quantiles and the SLO engine can count
slower-than-threshold requests over a sliding window — reservoirs can
do neither. When an observation lands while a trace context is bound
(:mod:`tpu_stencil.obs.context`), the bucket keeps the latest
``(trace_id, value)`` pair as its **exemplar**: the ``/metrics``
exposition attaches it to the bucket line, so a populated tail bucket
links straight to ``/debug/trace/<id>``.
"""

from __future__ import annotations

import random
import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Tuple

from tpu_stencil.obs.context import current as _ctx_current

#: Default cumulative bucket boundaries (seconds for the latency
#: histograms; generic log-spaced bounds otherwise — the ``+Inf``
#: bucket makes them total either way). Chosen to straddle the serve
#: tiers' latency range: sub-ms cache hits to multi-second cold
#: compiles.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)

#: The label value of the catch-all bucket (OpenMetrics spelling).
INF_LE = "+Inf"


class Counter:
    """Monotonic counter."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (e.g. queue depth). Tracks its high-water mark
    so a snapshot taken after a burst still shows how deep the queue got."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._peak = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v
            if v > self._peak:
                self._peak = v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    @property
    def peak(self) -> float:
        with self._lock:
            return self._peak


class Histogram:
    """Latency/size distribution with bounded memory.

    Keeps every observation up to ``cap``, then reservoir-replaces
    (deterministic seed: snapshots are reproducible for a given
    observation sequence). ``count``/``sum`` stay exact regardless.
    """

    def __init__(self, cap: int = 8192,
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        self._lock = threading.Lock()
        self._cap = cap
        self._values: List[float] = []
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._rng = random.Random(0)
        self._buckets: Tuple[float, ...] = tuple(
            sorted({float(b) for b in buckets})
        )
        # The bucket label strings, computed once (repr round-trips
        # floats exactly, so snapshot keys survive the exposition's
        # parse round-trip verbatim); the final slot is +Inf.
        self._les: Tuple[str, ...] = tuple(
            repr(b) for b in self._buckets
        ) + (INF_LE,)
        self._bucket_counts: List[int] = [0] * len(self._les)
        # Per-bucket exemplar: the LATEST (trace_id, value) that landed
        # in the bucket while a trace context was bound — last writer
        # wins, so a tail bucket always names a recent straggler.
        self._exemplars: Dict[int, Tuple[str, float]] = {}

    def observe(self, v: float) -> None:
        v = float(v)
        # The bound trace context (if any) is the exemplar source; read
        # outside the lock — one contextvar get, no allocation.
        ctx = _ctx_current()
        tid = ctx.trace_id if ctx is not None else ""
        # Cumulative le semantics: the first boundary >= v owns the
        # observation (inclusive upper bound, like OpenMetrics).
        idx = bisect_left(self._buckets, v)
        with self._lock:
            self._count += 1
            self._sum += v
            if v > self._max:
                self._max = v
            self._bucket_counts[idx] += 1
            if tid:
                self._exemplars[idx] = (tid, v)
            if len(self._values) < self._cap:
                self._values.append(v)
            else:
                # Classic reservoir sampling: keep each of the n seen so
                # far with probability cap/n.
                j = self._rng.randrange(self._count)
                if j < self._cap:
                    self._values[j] = v

    @staticmethod
    def _nearest_rank(vals: List[float], p: float) -> float:
        k = min(len(vals) - 1, max(0, int(round(p / 100.0 * (len(vals) - 1)))))
        return vals[k]

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the reservoir. Pinned edge cases
        (tests/test_serve.py): empty histogram -> 0.0 (a scrape before
        first traffic must render, not raise); a single sample is every
        percentile; past ``cap`` the rank is over the reservoir while
        count/sum/max stay exact."""
        with self._lock:
            if not self._values:
                return 0.0
            vals = sorted(self._values)
        return self._nearest_rank(vals, p)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> Dict[str, float]:
        """One consistent view: every field reads under a single lock
        acquisition, so a snapshot taken mid-burst can never pair rep
        k's count with rep k+1's sum/max (the old per-property reads
        could, and read ``max`` with no lock at all)."""
        with self._lock:
            count, total, mx = self._count, self._sum, self._max
            vals = sorted(self._values)
            per_bucket = list(self._bucket_counts)
            exemplars = dict(self._exemplars)
        cum = 0
        buckets: Dict[str, int] = {}
        for le, n in zip(self._les, per_bucket):
            cum += n
            buckets[le] = cum
        snap = {
            "count": count,
            "sum": total,
            "mean": (total / count) if count else 0.0,
            "p50": self._nearest_rank(vals, 50) if vals else 0.0,
            "p99": self._nearest_rank(vals, 99) if vals else 0.0,
            "max": mx,
            "buckets": buckets,
        }
        if exemplars:
            # Keyed by bucket le; absent entirely when no traced
            # observation has landed yet (the exposition renders —
            # and its parser rebuilds — exactly what is here).
            snap["exemplars"] = {
                self._les[i]: {"trace_id": t, "value": v}
                for i, (t, v) in sorted(exemplars.items())
            }
        return snap


class Registry:
    """Named metric store; creation is idempotent per (kind, name)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge())

    def histogram(self, name: str, cap: int = 8192,
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        with self._lock:
            return self._histograms.setdefault(
                name, Histogram(cap, buckets or DEFAULT_BUCKETS)
            )

    def snapshot(self) -> dict:
        """The ``serve.stats()`` schema: plain JSON-serializable dict."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: c.value for k, c in sorted(counters.items())},
            "gauges": {
                k: {"value": g.value, "peak": g.peak}
                for k, g in sorted(gauges.items())
            },
            "histograms": {
                k: h.snapshot() for k, h in sorted(histograms.items())
            },
        }
