"""Thread-safe metrics registry for the serving engine.

The observability layer the reference program never needed (one process,
one image, one timer): a serving loop fields a stream of heterogeneous
requests, so the interesting numbers are *distributions* (queue wait,
batch latency) and *rates* (requests, rejections, padded-pixel waste),
not a single wall-clock. Everything is in-process and dependency-free:
``Registry.snapshot()`` returns a plain dict (the ``serve.stats()`` /
``--stats-json`` schema, documented in docs/SERVING.md).

Histograms keep a bounded deterministic reservoir: past ``cap``
observations each new sample evicts a pseudo-randomly chosen slot
(seeded ``random.Random``), so percentile queries stay O(cap log cap)
and memory stays bounded no matter how long the server runs — the same
never-unbounded discipline as the request queue.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List


class Counter:
    """Monotonic counter."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (e.g. queue depth). Tracks its high-water mark
    so a snapshot taken after a burst still shows how deep the queue got."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._peak = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v
            if v > self._peak:
                self._peak = v

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    @property
    def peak(self) -> float:
        with self._lock:
            return self._peak


class Histogram:
    """Latency/size distribution with bounded memory.

    Keeps every observation up to ``cap``, then reservoir-replaces
    (deterministic seed: snapshots are reproducible for a given
    observation sequence). ``count``/``sum`` stay exact regardless.
    """

    def __init__(self, cap: int = 8192) -> None:
        self._lock = threading.Lock()
        self._cap = cap
        self._values: List[float] = []
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._rng = random.Random(0)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if v > self._max:
                self._max = v
            if len(self._values) < self._cap:
                self._values.append(v)
            else:
                # Classic reservoir sampling: keep each of the n seen so
                # far with probability cap/n.
                j = self._rng.randrange(self._count)
                if j < self._cap:
                    self._values[j] = v

    @staticmethod
    def _nearest_rank(vals: List[float], p: float) -> float:
        k = min(len(vals) - 1, max(0, int(round(p / 100.0 * (len(vals) - 1)))))
        return vals[k]

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile over the reservoir. Pinned edge cases
        (tests/test_serve.py): empty histogram -> 0.0 (a scrape before
        first traffic must render, not raise); a single sample is every
        percentile; past ``cap`` the rank is over the reservoir while
        count/sum/max stay exact."""
        with self._lock:
            if not self._values:
                return 0.0
            vals = sorted(self._values)
        return self._nearest_rank(vals, p)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> Dict[str, float]:
        """One consistent view: every field reads under a single lock
        acquisition, so a snapshot taken mid-burst can never pair rep
        k's count with rep k+1's sum/max (the old per-property reads
        could, and read ``max`` with no lock at all)."""
        with self._lock:
            count, total, mx = self._count, self._sum, self._max
            vals = sorted(self._values)
        return {
            "count": count,
            "sum": total,
            "mean": (total / count) if count else 0.0,
            "p50": self._nearest_rank(vals, 50) if vals else 0.0,
            "p99": self._nearest_rank(vals, 99) if vals else 0.0,
            "max": mx,
        }


class Registry:
    """Named metric store; creation is idempotent per (kind, name)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge())

    def histogram(self, name: str, cap: int = 8192) -> Histogram:
        with self._lock:
            return self._histograms.setdefault(name, Histogram(cap))

    def snapshot(self) -> dict:
        """The ``serve.stats()`` schema: plain JSON-serializable dict."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: c.value for k, c in sorted(counters.items())},
            "gauges": {
                k: {"value": g.value, "peak": g.peak}
                for k, g in sorted(gauges.items())
            },
            "histograms": {
                k: h.snapshot() for k, h in sorted(histograms.items())
            },
        }
