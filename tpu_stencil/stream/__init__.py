"""Pipelined multi-frame streaming: read → H2D → compute → D2H → write.

The streaming analog of :func:`tpu_stencil.driver.run_job`: instead of
one image per invocation, a whole frame stream flows through a 5-stage
software pipeline with a depth-``k`` dispatch-ahead window, so host I/O
and PCIe transfers overlap TPU compute and steady-state throughput is
bounded by the slowest *stage*, not the serial *sum* of stages (see
docs/STREAMING.md). Three pieces:

* :mod:`~tpu_stencil.stream.frames` — ``FrameSource``/``FrameSink``
  over concatenated headerless ``.raw`` streams (files, FIFOs, stdin/
  stdout), sorted frame directories, and a null sink for benchmarking.
* :mod:`~tpu_stencil.stream.engine` — the bounded-ring prefetch reader,
  the dispatch-ahead compute window (reusing ``driver.prepare_engine``
  — plans/filters/geometry apply unchanged, device buffers donated),
  and the in-order drain/writer, with backpressure and fail-with-frame
  -index error propagation throughout.
* :mod:`~tpu_stencil.stream.cli` — ``python -m tpu_stencil stream``.

>>> from tpu_stencil.config import ImageType, StreamConfig
>>> from tpu_stencil.stream import run_stream
>>> cfg = StreamConfig("clip.raw", 640, 480, 10, ImageType.RGB,
...                    output="null", frames=None)
>>> result = run_stream(cfg)
"""

from tpu_stencil.stream.engine import StreamFailure, StreamResult, run_stream
from tpu_stencil.stream.frames import (
    FrameSink,
    FrameSource,
    NullSink,
    RawDirectorySink,
    RawDirectorySource,
    RawStreamSink,
    RawStreamSource,
    open_sink,
    open_source,
)

__all__ = [
    "FrameSink",
    "FrameSource",
    "NullSink",
    "RawDirectorySink",
    "RawDirectorySource",
    "RawStreamSink",
    "RawStreamSource",
    "StreamFailure",
    "StreamResult",
    "open_sink",
    "open_source",
    "run_stream",
]
