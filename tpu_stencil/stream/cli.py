"""``python -m tpu_stencil stream`` — the pipelined multi-frame CLI.

Reference-compatible positionals (the run CLI's contract, extended to a
stream): ``input width height repetitions {grey,rgb}`` where ``input``
is a concatenated headerless ``.raw`` stream (file, FIFO, or ``-`` for
stdin) or a directory of per-frame ``.raw`` files. Exactly one of
``--frames N`` (the stream holds N frames; ending early is an error)
or ``--until-eof`` (process until the source runs dry) selects the
length contract. See docs/STREAMING.md.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from tpu_stencil.config import (
    OVERLAP_MODES,  # noqa: F401  (vocabulary parity with run/serve)
    PALLAS_SCHEDULES,
    ImageType,
    StreamConfig,
)

# --stats-json payload schema. 1 = the fields documented in
# docs/STREAMING.md. Bump on breaking shape changes.
STATS_SCHEMA_VERSION = 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tpu_stencil stream",
        description=(
            "Pipelined multi-frame streaming: read -> H2D -> compute -> "
            "D2H -> write with a depth-k dispatch-ahead window, so host "
            "I/O and PCIe transfers overlap TPU compute."
        ),
    )
    p.add_argument(
        "input",
        help="frame stream: concatenated headerless .raw (file or FIFO), "
             "'-' for stdin, or a directory of per-frame .raw files",
    )
    p.add_argument("width", type=int, help="frame width in pixels")
    p.add_argument("height", type=int, help="frame height in pixels")
    p.add_argument("repetitions", type=int,
                   help="filter applications per frame")
    p.add_argument(
        "image_type", choices=[t.value for t in ImageType],
        help="grey (1 byte/px) or rgb (3 interleaved bytes/px)",
    )
    n = p.add_mutually_exclusive_group(required=True)
    n.add_argument(
        "--frames", type=int, default=None, metavar="N",
        help="the stream holds exactly N frames; a stream that ends "
             "early fails with the frame index",
    )
    n.add_argument(
        "--until-eof", action="store_true",
        help="process frames until the source reaches EOF",
    )
    p.add_argument(
        "--filter", dest="filter_name", default="gaussian",
        help="filter name (box|gaussian|edge|...); default gaussian",
    )
    p.add_argument(
        "--backend", default="auto",
        choices=["auto", "xla", "pallas", "reference", "autotune"],
        help="compute backend, same vocabulary as the run CLI",
    )
    p.add_argument(
        "--schedule", default=None, choices=list(PALLAS_SCHEDULES),
        help="force the Pallas per-rep schedule (see docs/KERNEL.md)",
    )
    p.add_argument(
        "--boundary", default="zero", choices=["zero", "periodic"],
        help="edge semantics, same vocabulary as the run CLI",
    )
    p.add_argument(
        "--block-h", dest="block_h", type=int, default=None, metavar="ROWS",
        help="force the Pallas kernel's rows-per-grid-program",
    )
    p.add_argument(
        "--fuse", type=int, default=None, metavar="REPS",
        help="force the Pallas kernel's fused reps per HBM round-trip",
    )
    p.add_argument(
        "--output", default=None,
        help="sink: concatenated stream file, a directory (per-frame "
             "files), '-' for stdout, or 'null' to discard (benchmark "
             "mode); default blur_<input> beside a path input",
    )
    p.add_argument(
        "--pipeline-depth", type=int, default=2, metavar="K",
        help="dispatch-ahead window: at most K frames between the "
             "reader and the writer queue (1 = serial stages; "
             "default 2 = double buffering)",
    )
    p.add_argument(
        "--ring", dest="ring_buffers", type=int, default=None, metavar="N",
        help="host staging buffers the prefetch reader fills "
             "(default pipeline_depth + 2; must be > pipeline_depth)",
    )
    p.add_argument(
        "--mesh-frames", dest="mesh_frames", type=int, default=1,
        metavar="N",
        help="mesh fan-out: round-robin frames across N devices, one "
             "pipeline lane (staging ring + dispatch-ahead window) per "
             "device, in-order drain across devices (docs/STREAMING.md "
             "'Mesh fan-out'). 1 = single-device (default); N > 1 fails "
             "loudly when fewer devices exist; 0 = auto — a measured "
             "single-vs-mesh A/B enables fan-out only when it is "
             "strictly faster. Bit-exact in every mode; checkpoints "
             "record the device count and per-device cursors, so "
             "--resume under a different count fails typed",
    )
    p.add_argument(
        "--shard-frames", dest="shard_frames", default=None,
        metavar="RxC",
        help="spatially shard every in-flight frame over an RxC device "
             "mesh (docs/STREAMING.md 'Spatially sharded frames') — the "
             "route for frames too big for one device's HBM: the mesh "
             "program is the SAME cached ShardedRunner serve's "
             "oversized-request path compiles (one shared cache), with "
             "the per-edge persistent exchange (--overlap, default "
             "edge) threaded through the rep loop and H2D/D2H split "
             "per shard. 0 = auto (a measured single-vs-sharded A/B, "
             "cached; frames past the per-device HBM feasibility bound "
             "shard without a probe). Frames below --shard-min-pixels "
             "stay single-device (serve's routing discipline). "
             "Composes with --mesh-frames and --pipe-stages (all "
             "composed axes must be explicit); bit-exact; checkpoints "
             "record the topology, so --resume under a different RxC "
             "fails typed",
    )
    p.add_argument(
        "--pipe-stages", dest="pipe_stages", type=int, default=1,
        metavar="K",
        help="temporal pipeline: split the rep loop into K contiguous "
             "stages, each pinned to a mesh slice, frames flowing "
             "systolically stage-to-stage over ICI inside ONE "
             "persistent program — no host round-trip between stages "
             "(docs/STREAMING.md 'Temporal pipeline'). 1 = off "
             "(default); K > 1 fails loudly when the composed device "
             "budget (mesh-frames x K x RxC) exceeds what exists; 0 = "
             "auto — gated first by the roofline fill/drain model, "
             "then a measured single-vs-pipeline A/B enables stages "
             "only when strictly faster (verdict cached). Composes "
             "with --mesh-frames (independent pipeline groups) and "
             "--shard-frames (each stage an RxC spatial mesh) under "
             "the three-axis placement model; fill/drain is explicit, "
             "so short streams (frames < K) stay bit-exact; "
             "checkpoints record the stage count, so --resume under a "
             "different K fails typed",
    )
    p.add_argument(
        "--shard-min-pixels", dest="shard_min_pixels", type=int,
        default=1 << 20, metavar="PX",
        help="sharded-frame routing threshold in true pixels (H*W), "
             "the serve discipline: frames below it stay single-device "
             "even under --shard-frames (default 1 Mpx)",
    )
    p.add_argument(
        "--overlap", default="edge", choices=list(OVERLAP_MODES),
        help="compute/communication overlap schedule of the "
             "--shard-frames mesh program, same vocabulary as the run "
             "CLI; default edge (per-edge persistent double-buffered "
             "exchange in the rep-loop carry; degenerate tiles degrade "
             "to off, report-what-ran). Ignored without --shard-frames",
    )
    p.add_argument(
        "--checkpoint-every", type=int, default=0, metavar="N",
        help="commit a frame-index checkpoint every N written frames "
             "(0 = off); needs a resumable sink (file or directory)",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="resume past the frames a matching checkpoint records",
    )
    p.add_argument(
        "--progress-every", type=int, default=0, metavar="N",
        help="print the frame index to stderr every N written frames",
    )
    p.add_argument(
        "--dispatch-timeout", dest="dispatch_timeout_s", type=float,
        default=0.0, metavar="SECONDS",
        help="watchdog window around the drain's compute fence: a hung "
             "dispatch fails typed (DispatchTimeout) instead of parking "
             "the pipeline forever (0 = off, unless "
             "TPU_STENCIL_DISPATCH_TIMEOUT arms an env default)",
    )
    p.add_argument(
        "--io-retries", dest="io_retries", type=int, default=2,
        metavar="N",
        help="transient-I/O retries per frame read/write (rewindable "
             "sources and idempotent sinks only; default 2)",
    )
    p.add_argument(
        "--engine-restarts", dest="max_engine_restarts", type=int,
        default=1, metavar="N",
        help="mid-stream engine restarts after a transient h2d/compute/"
             "d2h fault: re-prepare the engine and resume from the "
             "frame checkpoint (needs --checkpoint-every and a file/"
             "directory input; default 1, 0 = off)",
    )
    p.add_argument(
        "--no-verify-ingest", dest="verify_ingest",
        action="store_false",
        help="disable ingest integrity (on by default: each frame is "
             "CRC32C'd as the reader stages it and re-verified at the "
             "H2D boundary, so a torn staging buffer fails typed "
             "before a device launch — docs/RESILIENCE.md 'Integrity "
             "model')",
    )
    p.add_argument(
        "--witness-rate", dest="witness_rate", type=float,
        default=1.0 / 256.0, metavar="RATE",
        help="fraction of frames re-executed through a different "
             "measured-equivalent program in the writer and compared "
             "bit-exact BEFORE the frame reaches the sink (seeded, "
             "deterministic; a divergence fails the run typed with the "
             "frame withheld; default 1/256, 0 = off; never applied "
             "past 512 reps)",
    )
    p.add_argument(
        "--faults", default=None, metavar="SPEC",
        help="arm the fault-injection harness (chaos testing / failure "
             "reproduction); same grammar as TPU_STENCIL_FAULTS, which "
             "this flag overrides (docs/RESILIENCE.md)",
    )
    p.add_argument(
        "--platform", default=None, choices=["cpu", "tpu", "gpu"],
        help="force the JAX platform via the config API before "
             "backend init",
    )
    p.add_argument(
        "--stats-json", default=None, metavar="PATH",
        help="dump the run summary (frames, frames/s, per-stage "
             "seconds) as versioned JSON to PATH ('-' = stdout)",
    )
    p.add_argument(
        "--trace", default=None, metavar="PATH",
        help="span tracing (tpu_stencil.obs): write a Chrome "
             "trace-event JSON of the pipeline ladder (stream.read/"
             "h2d/compute/d2h/write, one track per pipeline thread)",
    )
    p.add_argument(
        "--breakdown", action="store_true",
        help="print the per-stage pipeline table with the roofline "
             "steady-state bound (max(stage), with the PCIe H2D/D2H "
             "terms); implies span tracing for this run",
    )
    p.add_argument(
        "--metrics-text", default=None, metavar="PATH",
        help="write the driver-side metrics registry (stream_* "
             "histograms, stream_inflight_depth gauge) as "
             "Prometheus-style text to PATH ('-' = stdout)",
    )
    p.add_argument(
        "--flightrec-dir", dest="flightrec_dir", default=None,
        metavar="DIR",
        help="install the always-on flight recorder with this anomaly-"
             "dump spool: witness mismatches and torn-staging checksum "
             "failures dump the frame's spans (trace id analog "
             "frame-<i>) as capped JSON files; "
             "TPU_STENCIL_FLIGHTREC_DIR overrides "
             "(docs/OBSERVABILITY.md)",
    )
    return p


def _parse_shard_frames(parser, value):
    """``RxC`` -> (R, C); ``0`` -> (0, 0) (auto); None passes through.
    Jax-free, like every CLI validation here."""
    if value is None:
        return None
    if value == "0":
        return (0, 0)
    r, sep, c = value.lower().partition("x")
    if not sep or not r.isdigit() or not c.isdigit() \
            or int(r) < 1 or int(c) < 1:
        parser.error(
            f"--shard-frames must be RxC with positive integers, or 0 "
            f"for auto, got {value!r}"
        )
    return (int(r), int(c))


def main(argv=None) -> int:
    parser = build_parser()
    ns = parser.parse_args(argv)
    shard_frames = _parse_shard_frames(parser, ns.shard_frames)
    try:
        cfg = StreamConfig(
            input=ns.input,
            width=ns.width,
            height=ns.height,
            repetitions=ns.repetitions,
            image_type=ImageType(ns.image_type),
            filter_name=ns.filter_name,
            backend=ns.backend,
            output=ns.output,
            frames=ns.frames,
            schedule=ns.schedule,
            boundary=ns.boundary,
            block_h=ns.block_h,
            fuse=ns.fuse,
            pipeline_depth=ns.pipeline_depth,
            ring_buffers=ns.ring_buffers,
            mesh_frames=ns.mesh_frames,
            shard_frames=shard_frames,
            pipe_stages=ns.pipe_stages,
            shard_min_pixels=ns.shard_min_pixels,
            overlap=ns.overlap,
            checkpoint_every=ns.checkpoint_every,
            progress_every=ns.progress_every,
            dispatch_timeout_s=ns.dispatch_timeout_s,
            io_retries=ns.io_retries,
            max_engine_restarts=ns.max_engine_restarts,
            verify_ingest=ns.verify_ingest,
            witness_rate=ns.witness_rate,
        )
        out_spec = cfg.output_path  # stdin + no --output dies here, pre-jax
    except ValueError as e:
        parser.error(str(e))
    if ns.faults is not None:
        from tpu_stencil.resilience import faults as _faults

        try:
            _faults.configure(ns.faults)
        except ValueError as e:
            parser.error(str(e))
    # A stdout sink owns stdout: the binary frame stream must never be
    # interleaved with report text (a consumer piping '--output -' would
    # read corrupted frames), so the human summary moves to stderr and
    # the other stdout writers are refused.
    to_stdout_sink = out_spec == "-"
    if to_stdout_sink and ("-" in (ns.stats_json, ns.metrics_text)):
        parser.error(
            "--output - owns stdout; write --stats-json/--metrics-text "
            "to a file instead of '-'"
        )
    report_out = sys.stderr if to_stdout_sink else sys.stdout
    if ns.platform:
        import jax

        jax.config.update("jax_platforms", ns.platform)
    tracing = bool(ns.trace or ns.breakdown)
    if tracing:
        from tpu_stencil import obs

        obs.enable()
    if ns.flightrec_dir:
        from tpu_stencil.obs import flight as _flight

        _flight.install(spool_dir=ns.flightrec_dir)
    try:
        from tpu_stencil.stream.engine import StreamFailure, run_stream

        try:
            result = run_stream(cfg, resume=ns.resume)
        except StreamFailure as e:
            print(f"stream FAILED: {e}", file=sys.stderr)
            return 1
        except ValueError as e:
            # Runtime-discovered usage errors (non-resumable sink with
            # --checkpoint-every, a checkpoint from a different job on
            # --resume): clean message + nonzero, never a traceback.
            print(f"stream: {e}", file=sys.stderr)
            return 2
        if tracing:
            _report_observability(ns, cfg, result, report_out)
    finally:
        if tracing:
            from tpu_stencil import obs

            obs.disable()
    if ns.metrics_text:
        from tpu_stencil import obs

        obs.exposition.write_text(
            ns.metrics_text, obs.snapshot(), prefix="tpu_stencil_driver"
        )
    stages = " ".join(
        f"{k}={v:.3f}s" for k, v in sorted(result.stage_seconds.items())
        if v > 0
    )
    print(
        f"streamed {result.frames} frame(s)"
        + (f" (+{result.skipped} resumed)" if result.skipped else "")
        + (f" (engine restarted {result.restarts}x)"
           if result.restarts else "")
        + f" in {result.wall_seconds:.3f}s "
        f"({result.frames_per_second:.2f} frames/s, "
        f"depth={result.pipeline_depth}, backend={result.backend}"
        + (f" schedule={result.schedule}" if result.schedule else "")
        + (f" shard-frames={result.shard_frames[0]}x"
           f"{result.shard_frames[1]}"
           if result.shard_frames else "")
        + (f" pipe-stages={result.pipe_stages}"
           if result.pipe_stages > 1 else "")
        + (f" mesh-frames={result.n_devices}dev"
           if result.n_devices > 1 and not result.shard_frames
           and result.pipe_stages == 1 else "")
        + ")", file=report_out,
    )
    if result.per_device_frames and len(result.per_device_frames) > 1:
        # Mesh fan lanes, or pipeline groups under a composed topology.
        print(
            "per-device frames: "
            + " ".join(f"dev{d}={c}"
                       for d, c in enumerate(result.per_device_frames)),
            file=report_out,
        )
    if stages:
        print(f"stage seconds: {stages}", file=report_out)
    print(f"wrote {out_spec}" if out_spec != "null" else "sink: null",
          file=report_out)
    if ns.stats_json:
        payload = {
            "schema_version": STATS_SCHEMA_VERSION,
            "ts": time.monotonic(),
            "frames": result.frames,
            "skipped": result.skipped,
            "wall_seconds": result.wall_seconds,
            "frames_per_second": result.frames_per_second,
            "stage_seconds": result.stage_seconds,
            "backend": result.backend,
            "schedule": result.schedule,
            "pipeline_depth": result.pipeline_depth,
            "restarts": result.restarts,
            "n_devices": result.n_devices,
            "per_device_frames": result.per_device_frames,
            "shard_frames": (
                list(result.shard_frames) if result.shard_frames else None
            ),
            "pipe_stages": result.pipe_stages,
            "output": out_spec,
        }
        text = json.dumps(payload, indent=2, sort_keys=True)
        if ns.stats_json == "-":
            print(text)
        else:
            with open(ns.stats_json, "w") as fh:
                fh.write(text + "\n")
            print(f"wrote {ns.stats_json}", file=report_out)
    return 0


def _report_observability(ns, cfg: StreamConfig, result, out) -> None:
    from tpu_stencil import obs

    tracer = obs.get_tracer()
    if ns.trace:
        wrote = obs.export.write_chrome_trace(ns.trace, tracer)
        if wrote:
            print(f"wrote trace {wrote}", file=out)
    if ns.breakdown:
        halo = None
        if result.shard_frames:
            # The ICI ghost model needs the filter halo; the filter
            # bank is pure numpy, so this stays jax-free.
            from tpu_stencil.filters import get_filter

            halo = get_filter(cfg.filter_name).halo
        print(obs.breakdown.render_breakdown(tracer), end="", file=out)
        print(obs.breakdown.render_stream(tracer, {
            "frame_bytes": cfg.frame_bytes,
            "reps": cfg.repetitions,
            "backend": result.backend,
            "filter_name": cfg.filter_name,
            "h_img": cfg.height,
            "w_img": cfg.width,
            "channels": cfg.channels,
            "block_h": cfg.block_h,
            "fuse": cfg.fuse,
            "pipeline_depth": result.pipeline_depth,
            "frames": result.frames,
            "wall_seconds": result.wall_seconds,
            "n_devices": result.n_devices,
            "shard_frames": result.shard_frames,
            "pipe_stages": result.pipe_stages,
            "halo": halo,
        }), end="", file=out)
        print(obs.breakdown.render_resilience(obs.snapshot()),
              end="", file=out)


if __name__ == "__main__":
    sys.exit(main())
