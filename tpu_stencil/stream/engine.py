"""The pipelined streaming engine: read → H2D → compute → D2H → write.

``run_job`` is the serial shape — load, iterate, store, one frame per
invocation, throughput bounded by the *sum* of the stages. This engine
is the software-pipelined shape the paper's MPI variant applies at the
halo boundary (overlap communication with interior compute,
``mpi/mpi_convolution.c:194-224``; PR 4), applied at the host↔device
boundary for frame streams: persistent channels amortized across
iterations (arXiv:2508.13370) and stage-pipelined execution
(arXiv:1907.06154). Steady-state throughput is bounded by the slowest
*stage* (:func:`tpu_stencil.runtime.roofline.stream_frames_per_second`).

Shape of the machine (docs/STREAMING.md has the diagram):

* **reader thread** — fills reusable host staging buffers from the
  :class:`~tpu_stencil.stream.frames.FrameSource`. The buffers form a
  bounded ring: the reader blocks when every buffer is in flight
  (backpressure, never unbounded buffering).
* **dispatch window** — the main thread takes filled buffers in order,
  ``jax.device_put``\\ s them and launches the compiled step (the SAME
  program ``driver.prepare_engine`` warm-compiles — plans, filters,
  schedules, fuse and geometry all apply unchanged; the device input is
  donated, so XLA reuses it for the output and steady state allocates
  nothing new on device). At most ``pipeline_depth`` frames may be past
  the reader and not yet drained: depth 1 degenerates to the serial
  stage chain, depth k overlaps frame i+1's read/H2D/compute with frame
  i's drain.
* **drain thread** — fences each frame's compute in dispatch order
  (``stream.compute`` spans dispatch → device finished, so overlapped
  compute is attributed to compute, not to whichever drain wait
  observed it), copies the result D2H, releases the frame's window
  slot. (The staging buffer already returned to the ring when the
  fenced H2D span closed.)
* **writer thread** — writes results in order to the
  :class:`~tpu_stencil.stream.frames.FrameSink`, commits the
  frame-index checkpoint (``runtime/checkpoint.py``) and emits the
  progress heartbeat.

Failure semantics: the first failing stage records (stage, frame index,
exception) and stops the pipeline; already-dispatched frames drain,
already-written frames stay written (with ``--checkpoint-every`` the
job resumes past them), and :func:`run_stream` raises
:class:`StreamFailure` naming the frame. Clean EOF propagates as
sentinels through every queue.

Observability (PR 2 machinery): ``stream.read`` / ``stream.h2d`` /
``stream.compute`` / ``stream.d2h`` / ``stream.write`` spans (one trace
track per pipeline thread — a ``--trace`` of a depth-2 run shows the
pipeline ladder), a ``stream_inflight_depth`` gauge, per-stage
``stream_<stage>_seconds`` histograms and a ``stream_frames_total``
counter in the driver registry.
"""

from __future__ import annotations

import dataclasses
import queue
import sys
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from tpu_stencil import obs
from tpu_stencil.config import StreamConfig
from tpu_stencil.obs import context as _obs_ctx
from tpu_stencil.obs import flight as _obs_flight
from tpu_stencil.obs import tracing as _obs_tracing
from tpu_stencil.integrity import checksum as _checksum
from tpu_stencil.integrity import witness as _witness_mod
from tpu_stencil.resilience import deadline as _deadline
from tpu_stencil.resilience import faults as _faults
from tpu_stencil.resilience import retry as _retry
from tpu_stencil.stream import frames as frames_io

_EOF = object()          # clean end-of-stream sentinel
_STAGES = ("read", "h2d", "compute", "d2h", "write")


class StreamFailure(RuntimeError):
    """A stage failed on a specific frame; the pipeline drained and
    stopped. ``stage`` names the failing stage, ``frame_index`` the
    frame (global index, resume-aware), ``__cause__`` the original
    exception."""

    def __init__(self, stage: str, frame_index: int, cause: BaseException):
        super().__init__(
            f"stream {stage} failed at frame {frame_index}: "
            f"{type(cause).__name__}: {cause}"
        )
        self.stage = stage
        self.frame_index = frame_index


@dataclasses.dataclass
class StreamResult:
    """One finished (or resumed-and-finished) streaming job."""

    frames: int              # frames processed THIS run
    skipped: int             # frames skipped by --resume
    wall_seconds: float      # whole run incl. warm-up compile
    frames_per_second: float # frames / wall_seconds
    # Total busy seconds per stage. On a mesh-fan run the per-device
    # stages (h2d/compute/d2h) SUM across all lanes (n busy lanes can
    # exceed wall x1); the --breakdown bottleneck comparison divides
    # them by n_devices so the serial read/write stages compare fairly.
    stage_seconds: Dict[str, float]
    backend: str             # report-what-ran, like JobResult
    schedule: Optional[str]
    pipeline_depth: int
    output: str
    restarts: int = 0        # mid-stream engine restarts that recovered
    # Mesh fan-out (tpu_stencil.parallel.fanout): the device count that
    # actually ran (report-what-ran — --mesh-frames 0 resolves by a
    # measured A/B before this is set) and, when n_devices > 1, the
    # frames each device's lane completed this run.
    n_devices: int = 1
    per_device_frames: Optional[list] = None
    # Spatially sharded frames (tpu_stencil.stream.sharded): the RxC
    # topology each frame sharded over, or None (report-what-ran —
    # --shard-frames 0 and the shard_min_pixels routing discipline
    # resolve before this is set; n_devices is then R*C).
    shard_frames: Optional[Tuple[int, int]] = None
    # Temporal pipeline (tpu_stencil.stream.pipelined): the stage count
    # frames flowed through, 1 = no pipeline (report-what-ran —
    # --pipe-stages 0 resolves by the roofline gate + measured A/B
    # before this is set). Under composition n_devices is the full
    # three-axis budget: groups * pipe_stages * R * C.
    pipe_stages: int = 1


class _Abort(Exception):
    """Internal: a sibling stage failed; unwind quietly."""


class _StageControl:
    """Stop flag, first-failure slot, abort-aware polling queue ops and
    the per-stage span/clock machinery — the control surface both
    engines share (:class:`_Pipeline` extends it; the mesh fan-out's
    lanes use it directly, :mod:`tpu_stencil.parallel.fanout`), so the
    teardown/attribution protocol can never drift between them."""

    def __init__(self) -> None:
        self.stop = threading.Event()
        self._fail_lock = threading.Lock()
        self.failure: Optional[Tuple[str, int, BaseException]] = None
        self._stage_lock = threading.Lock()
        self.stage_seconds: Dict[str, float] = {s: 0.0 for s in _STAGES}
        # Run start, for the live per-stage utilization gauges
        # (stream_<stage>_busy_fraction = busy seconds / wall seconds
        # so far): the time-series sampler turns these into the
        # per-stage utilization trends the roofline comparison reads.
        self.t_start = time.perf_counter()

    def fail(self, stage: str, frame_index: int, exc: BaseException) -> None:
        with self._fail_lock:
            if self.failure is None:
                self.failure = (stage, frame_index, exc)
        self.stop.set()

    def _check(self) -> None:
        if self.stop.is_set():
            raise _Abort()

    def put(self, q: queue.Queue, item) -> None:
        """Blocking put that aborts when a sibling stage failed — a
        stalled downstream queue must not deadlock the teardown."""
        while True:
            self._check()
            try:
                q.put(item, timeout=0.05)
                return
            except queue.Full:
                pass

    def get(self, q: queue.Queue):
        while True:
            self._check()
            try:
                return q.get(timeout=0.05)
            except queue.Empty:
                pass

    def stage(self, name: str, frame_index: int, t0: float = None,
              **attrs):
        """Span + per-stage clock for one frame in one stage. ``t0``
        backdates the span's open (and the clock) to when the stage's
        work really began — the compute stage runs on-device from its
        *dispatch*, not from when the drain thread gets around to
        fencing it, and an open-at-fence span would under-measure
        compute by however long it overlapped the previous frame's
        drain (misnaming the bottleneck stage in ``--breakdown``).
        ``attrs`` land on the span record (the mesh fan-out tags its
        per-device stages with ``dev=``)."""
        return _StageSpan(self, name, frame_index, t0, **attrs)


class _Pipeline(_StageControl):
    """Shared state of one run: queues, window, failure slot, clocks."""

    def __init__(self, cfg: StreamConfig):
        super().__init__()
        self.cfg = cfg
        n_ring = cfg.ring_size
        self.ring = [
            np.empty(cfg.frame_bytes, np.uint8) for _ in range(n_ring)
        ]
        self.free_q: queue.Queue = queue.Queue()
        for i in range(n_ring):
            self.free_q.put(i)
        self.filled_q: queue.Queue = queue.Queue(maxsize=n_ring)
        self.inflight_q: queue.Queue = queue.Queue(maxsize=cfg.pipeline_depth)
        self.write_q: queue.Queue = queue.Queue(maxsize=cfg.pipeline_depth + 1)
        # The dispatch-ahead window: a frame holds a slot from read start
        # until its D2H completes, so at most pipeline_depth frames are
        # anywhere between the source and the writer queue.
        self.window = threading.Semaphore(cfg.pipeline_depth)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._gauge = obs.registry().gauge("stream_inflight_depth")
        # Witness sampling (tpu_stencil.integrity): decided in the
        # READER (the only stage holding the pristine input — the ring
        # slot is recycled after H2D), executed in the writer. Disabled
        # past WITNESS_MAX_REPS: the eager witness executor is linear
        # in reps (docs/RESILIENCE.md "Integrity model").
        self.witness = (
            _witness_mod.WitnessSampler(cfg.witness_rate,
                                        seed=cfg.witness_seed)
            if (cfg.witness_rate > 0
                and cfg.repetitions <= _witness_mod.WITNESS_MAX_REPS)
            else None
        )

    def acquire_window(self) -> None:
        while not self.window.acquire(timeout=0.05):
            self._check()
        with self._inflight_lock:
            self._inflight += 1
            self._gauge.set(self._inflight)

    def release_window(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1
            self._gauge.set(self._inflight)
        self.window.release()

    def zero_gauge(self) -> None:
        """Teardown: a failed run's aborted in-flight frames never pass
        release_window, and the process-wide gauge must not keep
        reporting them forever (peak survives, as for every gauge)."""
        with self._inflight_lock:
            self._inflight = 0
            self._gauge.set(0)


class _StageSpan:
    __slots__ = ("_pl", "name", "frame_index", "_span", "_t0", "_attrs",
                 "_ctx_token")

    def __init__(self, pl: "_StageControl", name: str, frame_index: int,
                 t0: float = None, **attrs):
        self._pl, self.name, self.frame_index = pl, name, frame_index
        self._t0 = t0
        self._attrs = attrs

    def __enter__(self):
        # The frame index is the stream's trace-id analog: binding
        # ``frame-<i>`` for the span's duration stamps the record, so
        # /debug-style lookups and flight dumps correlate a frame's
        # read/h2d/compute/d2h/write exactly like a request's hops.
        # Only when a span sink is live — the disabled path stays free.
        self._ctx_token = (
            _obs_ctx.push(_obs_ctx.frame_context(self.frame_index))
            if _obs_tracing.sinks_active() else None
        )
        self._span = obs.span(
            f"stream.{self.name}", "stream", frame=self.frame_index,
            **self._attrs
        )
        self._span.__enter__()
        if self._t0 is None:
            self._t0 = time.perf_counter()
        elif hasattr(self._span, "_t0"):
            # Backdate the trace record too (no-op span when disabled).
            self._span._t0 = self._t0
        return self._span

    def __exit__(self, *exc) -> None:
        dt = time.perf_counter() - self._t0
        self._span.__exit__(*exc)
        if self._ctx_token is not None:
            _obs_ctx.pop(self._ctx_token)
        with self._pl._stage_lock:
            self._pl.stage_seconds[self.name] += dt
            busy = self._pl.stage_seconds[self.name]
        obs.registry().histogram(
            f"stream_{self.name}_seconds"
        ).observe(dt)
        # Live stage utilization: busy-fraction of the run's wall clock
        # so far (1.0 = the stage IS the pipeline's bottleneck).
        wall = time.perf_counter() - self._pl.t_start
        if wall > 0:
            obs.registry().gauge(
                f"stream_{self.name}_busy_fraction"
            ).set(min(1.0, busy / wall))


def _io_policy(cfg: StreamConfig) -> _retry.RetryPolicy:
    """The reader/writer transient-I/O policy: ``cfg.io_retries`` extra
    attempts on the shared short-backoff shape."""
    return dataclasses.replace(_retry.IO_POLICY, attempts=1 + cfg.io_retries)


def _make_read_frame(cfg: StreamConfig, source):
    """The per-frame read both engines (single-device and mesh fan-out)
    share: the ``read`` fault site resolved once, and transient
    failures retried under the shared policy — but only when the source
    can rewind (``source.mark()``): a pipe's consumed bytes are gone,
    so pipe errors propagate on the first failure."""
    fault = _faults.site("read")  # resolved once, NOT per frame
    policy = _io_policy(cfg)

    def read_frame(i: int, buf) -> bool:
        def attempt() -> bool:
            if fault is not None:
                fault(i)
            return source.read_into(buf)

        restore = source.mark()
        if restore is None:
            return attempt()
        return _retry.retry_call(
            attempt, policy=policy,
            on_retry=lambda _a, _e: restore(),
            label=f"stream.read[{i}]",
        )

    return read_frame


def _make_write_frame(cfg: StreamConfig, sink):
    """The per-frame write both engines share: the ``write`` fault site
    resolved once; idempotent sinks (positioned files, per-frame
    directory files, null) retry transient failures, append-only sinks
    fail on the first error — a retried partial write would duplicate
    bytes."""
    fault = _faults.site("write")  # resolved once, NOT per frame
    policy = _io_policy(cfg)
    retryable = bool(getattr(sink, "retryable_writes", False))

    def write_frame(i: int, frame) -> None:
        def attempt() -> None:
            if fault is not None:
                fault(i)
            sink.write(i, frame)

        if retryable:
            _retry.retry_call(attempt, policy=policy,
                              label=f"stream.write[{i}]")
        else:
            attempt()

    return write_frame


def _verify_staged(buf: np.ndarray, crc, idx: int) -> None:
    """The H2D-boundary re-verification: the staging slot must still
    hold the bytes the reader checksummed (``crc`` is None when
    ``verify_ingest`` is off). A mismatch is a torn host buffer —
    counted, and raised typed (:class:`ChecksumMismatch`, permanent:
    the frame's true bytes are gone, a restart cannot recover them)."""
    if crc is None:
        return
    try:
        _checksum.verify(buf, crc, f"stream staging ring (frame {idx})")
    except _checksum.ChecksumMismatch:
        obs.registry().counter("integrity_ingest_failures_total").inc()
        _obs_flight.trigger("checksum_mismatch",
                            trace_id=f"frame-{idx}", tier="stream",
                            frame=idx)
        raise
    obs.registry().counter("integrity_ingest_verified_total").inc()


def _witness_frame(cfg: StreamConfig, idx: int, wit_buf: np.ndarray,
                   arr: np.ndarray) -> None:
    """Re-execute one sampled frame through the eager measured-
    equivalent program and compare against the pipeline's result; a
    divergence raises typed (:class:`WitnessMismatch` → a ``write``-
    stage StreamFailure) BEFORE the frame reaches the sink."""
    with obs.span("integrity.witness", "stream", frame=idx):
        want = _witness_mod.device_witness(
            wit_buf.reshape(cfg.frame_shape), cfg.filter_name,
            cfg.repetitions, cfg.boundary,
        )
    obs.registry().counter("integrity_witness_total").inc()
    if not np.array_equal(want, np.asarray(arr)):
        obs.registry().counter("integrity_witness_mismatch_total").inc()
        _obs_flight.trigger("witness_mismatch",
                            trace_id=f"frame-{idx}", tier="stream",
                            frame=idx, reps=cfg.repetitions)
        raise _checksum.WitnessMismatch(
            f"stream frame {idx}",
            "frame withheld from the sink (two measured-equivalent "
            "programs disagree — hardware/runtime fault)",
        )


def _reader(pl: _Pipeline, source, start_frame: int) -> None:
    """Prefetch frames into the staging ring, honoring the dispatch
    window (a frame occupies a window slot from read start). Retry
    semantics: :func:`_make_read_frame`.

    Integrity at ingest: each filled buffer is CRC32C'd HERE (the
    moment the bytes arrive from the source), re-verified at the H2D
    boundary in the dispatcher — anything that tears the staging slot
    in between (the ``integrity.corrupt_ingest`` chaos site fires
    right after the CRC, simulating exactly that) fails typed before a
    device launch is burned. Witness sampling is also decided here:
    the ring slot is recycled after H2D, so a sampled frame's pristine
    input must be copied aside now."""
    cfg = pl.cfg
    idx = start_frame
    read_frame = _make_read_frame(cfg, source)
    fault_corrupt = _faults.site("integrity.corrupt_ingest")
    try:
        while cfg.frames is None or idx < cfg.frames:
            pl.acquire_window()
            buf_i = pl.get(pl.free_q)
            with pl.stage("read", idx):
                ok = read_frame(idx, pl.ring[buf_i])
            if not ok:
                if cfg.frames is not None:
                    raise IOError(
                        f"stream ended after {idx} frame(s); "
                        f"--frames promised {cfg.frames}"
                    )
                pl.free_q.put(buf_i)
                pl.release_window()
                break
            crc = (_checksum.crc32c(pl.ring[buf_i])
                   if cfg.verify_ingest else None)
            if fault_corrupt is not None and _checksum.fired(
                    fault_corrupt, idx):
                # In place: THE staging slot tears, like real memory.
                _checksum.corrupt_array(pl.ring[buf_i])
            wit = None
            if pl.witness is not None and pl.witness.pick():
                wit = pl.ring[buf_i].copy()
            pl.put(pl.filled_q, (idx, buf_i, crc, wit))
            idx += 1
        pl.put(pl.filled_q, _EOF)
    except _Abort:
        pass
    except BaseException as e:
        pl.fail("read", idx, e)


def _drain(pl: _Pipeline, eng: dict) -> None:
    """Fence compute in dispatch order, copy D2H, free the window slot,
    hand off to the writer. ``eng['fetch']`` is installed by the
    dispatcher's bootstrap before the first in-flight item is enqueued
    (the queue's lock orders the publication). The compute fence runs
    under the dispatch watchdog: a hung device raises a typed
    ``DispatchTimeout`` (surfaced as a ``compute``-stage StreamFailure)
    instead of parking the drain thread forever."""
    idx, stage = -1, "compute"
    fault_d2h = _faults.site("d2h")  # resolved once, NOT per frame
    fault_corrupt = _faults.site("integrity.corrupt_result")
    timeout_s = _deadline.resolve(pl.cfg.dispatch_timeout_s)
    try:
        while True:
            item = pl.get(pl.inflight_q)
            if item is _EOF:
                pl.put(pl.write_q, _EOF)
                return
            idx, out_dev, t_disp, wit = item
            stage = "compute"
            with pl.stage("compute", idx, t0=t_disp):
                _deadline.fence(out_dev, timeout_s,
                                f"stream.compute[frame={idx}]")
            stage = "d2h"
            with pl.stage("d2h", idx):
                if fault_d2h is not None:
                    fault_d2h(idx)
                arr = eng["fetch"](out_dev)
            if fault_corrupt is not None and _checksum.fired(
                    fault_corrupt, idx):
                arr = _checksum.corrupt_array(np.asarray(arr))
            pl.release_window()
            pl.put(pl.write_q, (idx, arr, wit))
    except _Abort:
        pass
    except BaseException as e:
        pl.fail(stage, max(idx, 0), e)


def _writer(pl: _Pipeline, sink, done: list, save_progress=None) -> None:
    """Write results in order; commit the frame-index checkpoint and the
    progress heartbeat. ``done[0]`` tracks frames fully written. Retry
    semantics: :func:`_make_write_frame`. ``save_progress`` (optional)
    overrides the checkpoint commit — the sharded-stream engine passes
    a closure stamping the RxC shard topology into the sidecar."""
    cfg = pl.cfg
    idx = -1
    write_frame = _make_write_frame(cfg, sink)
    try:
        while True:
            item = pl.get(pl.write_q)
            if item is _EOF:
                return
            idx, arr, wit = item
            if wit is not None:
                # Witness BEFORE the write: a frame that fails its
                # re-execution is withheld from the sink (the run fails
                # typed at this frame), never published.
                _witness_frame(cfg, idx, wit, arr)
            with pl.stage("write", idx):
                write_frame(idx, arr)
            done[0] = idx + 1
            obs.registry().counter("stream_frames_total").inc()
            if cfg.checkpoint_every and done[0] % cfg.checkpoint_every == 0:
                from tpu_stencil.runtime import checkpoint as ckpt

                sink.flush()
                if save_progress is not None:
                    save_progress(done[0])
                else:
                    ckpt.save_stream_progress(cfg, done[0])
            if cfg.progress_every and done[0] % cfg.progress_every == 0:
                print(f"stream: frame {done[0]}", file=sys.stderr, flush=True)
    except _Abort:
        pass
    except BaseException as e:
        pl.fail("write", max(idx, 0), e)


def _build_launch(model, cfg: StreamConfig):
    """The donated per-frame launcher — the exact program
    ``prepare_engine``'s warm-up compiled (same jit cache entry), called
    directly so the device input buffer is donated instead of
    defensively copied (``IteratedConv2D.__call__`` copies to protect
    callers; a stream frame has no other owner)."""
    import jax.numpy as jnp

    from tpu_stencil.models import blur

    resolved, schedule = model.resolved_config(
        (cfg.height, cfg.width), cfg.channels
    )
    bh, fz = model.resolved_geometry((cfg.height, cfg.width), cfg.channels)
    reps = jnp.int32(cfg.repetitions)

    def launch(dev):
        return blur.iterate(
            dev, reps, plan=model.plan, backend=resolved,
            boundary=cfg.boundary, schedule=schedule,
            block_h=bh, fuse=fz,
        )

    return launch, resolved, schedule


def _dispatch(pl: _Pipeline, model, devices, eng: dict) -> None:
    """The main-thread dispatch loop: bootstrap the engine on frame 0
    (``prepare_engine``'s warm-up compile overlaps the reader's
    prefetch of the following frames), then H2D + launch each filled
    frame inside the depth-``k`` window. Publishes ``fetch``/``backend``
    /``schedule`` into ``eng`` before the first in-flight item."""
    import jax

    from tpu_stencil import driver

    cfg = pl.cfg
    idx, stage = -1, "compute"  # bootstrap failures are compile/compute
    # Injection sites resolved once per run, before the frame loop —
    # the hot path branches on captured Nones (the zero-overhead
    # contract tests assert).
    fault_h2d = _faults.site("h2d")
    fault_compute = _faults.site("compute")
    try:
        first = pl.get(pl.filled_q)
        if first is _EOF:
            pl.put(pl.inflight_q, _EOF)
            return
        idx, b0, crc0, wit0 = first
        # First frame bootstraps the engine: prepare_engine places it
        # and runs the 0-rep warm-up compile whose output equals its
        # input — the warm device array IS frame 0's input, no second
        # transfer (the run_job discipline). prepare_engine checks the
        # h2d/compile injection sites itself. The staged CRC is
        # re-verified first: a torn slot must fail typed before the
        # warm-up compile is paid for corrupt pixels.
        stage = "h2d"
        _verify_staged(pl.ring[b0], crc0, idx)
        frame0 = pl.ring[b0].reshape(cfg.frame_shape)
        img_dev, _step_fn, fetch = driver.prepare_engine(
            model, frame0, devices
        )
        launch, backend, schedule = _build_launch(model, cfg)
        eng["fetch"] = fetch
        eng["backend"] = backend
        eng["schedule"] = schedule
        # prepare_engine fenced the warm-up, so frame 0's staging buffer
        # is already transferred: recycle its ring slot now and mark the
        # in-flight record bufferless.
        pl.free_q.put(b0)
        stage = "compute"
        if fault_compute is not None:
            fault_compute(idx)
        t_disp = time.perf_counter()
        out0 = launch(img_dev)
        pl.put(pl.inflight_q, (idx, out0, t_disp, wit0))
        while True:
            item = pl.get(pl.filled_q)
            if item is _EOF:
                break
            idx, bi, crc, wit = item
            stage = "h2d"
            if fault_h2d is not None:
                fault_h2d(idx)
            # The H2D-boundary re-verification: the staged bytes must
            # still match their ingest CRC, or the device launch is
            # refused typed (ChecksumMismatch — permanent, no restart).
            _verify_staged(pl.ring[bi], crc, idx)
            with pl.stage("h2d", idx) as s:
                # Fenced: device_put returns before the PCIe copy
                # lands, and an unfenced span would misattribute the
                # transfer to whoever blocks next (the drain's compute
                # fence) — the measured-vs-model PCIe comparison in
                # --breakdown depends on this attribution. The fence
                # only holds THIS frame's pre-compute path; earlier
                # frames keep computing on device.
                dev = s.fence(jax.device_put(
                    pl.ring[bi].reshape(cfg.frame_shape), devices[0]
                ))
            pl.free_q.put(bi)  # fenced H2D consumed the staging buffer
            stage = "compute"
            if fault_compute is not None:
                fault_compute(idx)
            t_disp = time.perf_counter()
            out = launch(dev)  # async dispatch; donates dev
            pl.put(pl.inflight_q, (idx, out, t_disp, wit))
        pl.put(pl.inflight_q, _EOF)
    except _Abort:
        pass
    except BaseException as e:
        pl.fail(stage, max(idx, 0), e)


def run_stream(
    cfg: StreamConfig,
    devices: Optional[list] = None,
    resume: bool = False,
    source: Optional[frames_io.FrameSource] = None,
    sink: Optional[frames_io.FrameSink] = None,
) -> StreamResult:
    """Run one streaming job end to end; returns :class:`StreamResult`
    or raises :class:`StreamFailure`. ``source``/``sink`` override the
    config's specs (tests and benchmarks inject synthetic stages).

    Mid-stream engine-fault recovery: when a *transient* failure hits an
    engine stage (h2d/compute/d2h) and the job checkpoints its progress
    (``checkpoint_every`` + a restartable path source — a regular file
    or frame directory, whose consumed frames can be re-served), the
    pipeline is torn down, the engine re-prepared ONCE per restart
    budget (``cfg.max_engine_restarts``), and the run resumes from the
    frame checkpoint — already-written frames stay written, the restart
    count lands in ``StreamResult.restarts`` and
    ``resilience_stream_restarts_total``. I/O-stage failures are
    handled *inside* the pipeline by the reader/writer retry policy and
    never restart the engine; injected source/sink objects skip
    restarts entirely (the caller owns their positioning).

    Mesh fan-out (``cfg.mesh_frames != 1``): the device count is
    resolved ONCE per call — explicit N, or the measured auto A/B
    (:func:`tpu_stencil.parallel.fanout.resolve_mesh_frames`) — and
    every restart of this run re-fans at the same width, so the
    checkpoint's per-device cursors stay aligned.

    Spatially sharded frames (``cfg.shard_frames``): the RxC topology
    is likewise resolved ONCE per call (explicit RxC above the
    ``shard_min_pixels`` routing threshold, or the measured /
    feasibility-forced auto verdict —
    :func:`tpu_stencil.stream.sharded.resolve_shard_frames`) and every
    restart re-shards at the SAME topology, so the checkpoint's
    recorded scatter layout stays aligned.

    Temporal pipeline (``cfg.pipe_stages != 1``): the stage count is
    resolved ONCE per call (explicit K, or the roofline-gated measured
    auto A/B — :func:`tpu_stencil.parallel.pipeline
    .resolve_pipe_stages`). Stages compose with the other two axes
    under the three-axis placement model: ``--mesh-frames G`` becomes G
    independent pipeline groups and ``--shard-frames RxC`` shards each
    stage spatially (:mod:`tpu_stencil.stream.pipelined` — also the
    route for mesh-of-sharded-groups at K = 1); the composed topology
    must be explicit on every active axis (the config contract), so no
    auto probe ever races another axis's resolution."""
    restarts = 0
    n_mesh = None
    pipe = None
    shard = _UNRESOLVED
    while True:
        try:
            if shard is _UNRESOLVED:
                shard = _resolve_shard_frames(cfg, devices)
            if pipe is None:
                pipe = _resolve_pipe_stages(cfg, devices)
            if n_mesh is None:
                if shard is not None or pipe > 1:
                    # Composed run: mesh_frames is explicit (the config
                    # refuses composed autos) — it is the group count,
                    # never re-resolved against another axis's devices.
                    n_mesh = cfg.mesh_frames if cfg.mesh_frames > 1 else 1
                else:
                    n_mesh = _resolve_mesh_frames(cfg, devices)
            result = _run_stream_once(cfg, devices, resume, source, sink,
                                      n_mesh=n_mesh, shard=shard,
                                      pipe=pipe)
            result.restarts = restarts
            return result
        except StreamFailure as e:
            restartable = (
                restarts < cfg.max_engine_restarts
                and source is None and sink is None
                and cfg.checkpoint_every > 0
                and e.stage in ("h2d", "compute", "d2h")
                and e.__cause__ is not None
                and _retry.is_transient(e.__cause__)
                and frames_io.is_restartable_source(cfg.input)
            )
            if not restartable:
                raise
            restarts += 1
            obs.registry().counter(
                "resilience_stream_restarts_total"
            ).inc()
            print(
                f"stream: engine fault at {e.stage}[frame "
                f"{e.frame_index}] ({type(e.__cause__).__name__}); "
                f"re-preparing engine and resuming from checkpoint "
                f"(restart {restarts}/{cfg.max_engine_restarts})",
                file=sys.stderr, flush=True,
            )
            resume = True  # honor whatever progress the checkpoint holds


def _finish_result(cfg: StreamConfig, resume: bool, t_start: float,
                   start_frame: int, frames: int, stage_seconds: Dict,
                   backend: str, schedule, out_spec: str,
                   n_devices: int = 1,
                   per_device_frames: Optional[list] = None,
                   shard_frames: Optional[Tuple[int, int]] = None,
                   pipe_stages: int = 1
                   ) -> StreamResult:
    """The shared run epilogue both engines (single-device and mesh
    fan-out) end in: sweep the progress sidecar of a completed run,
    then assemble the report-what-ran :class:`StreamResult` — one
    place, so the two paths can never drift on the completion
    contract."""
    if cfg.checkpoint_every or resume:
        from tpu_stencil.runtime import checkpoint as ckpt

        ckpt.clear_stream_progress(cfg)
    wall = time.perf_counter() - t_start
    return StreamResult(
        frames=frames,
        skipped=start_frame,
        wall_seconds=wall,
        frames_per_second=frames / wall if wall > 0 else 0.0,
        stage_seconds=stage_seconds,
        backend=backend,
        schedule=schedule if backend == "pallas" else None,
        pipeline_depth=cfg.pipeline_depth,
        output=out_spec,
        n_devices=n_devices,
        per_device_frames=per_device_frames,
        shard_frames=shard_frames,
        pipe_stages=pipe_stages,
    )


def _resolve_mesh_frames(cfg: StreamConfig, devices) -> int:
    """The device count this run fans over: 1 without ``--mesh-frames``
    (no jax import at all on that path), else the fanout resolver's
    verdict (explicit width, or the measured auto A/B)."""
    if cfg.mesh_frames == 1:
        return 1
    import jax

    from tpu_stencil.parallel import fanout

    devs = devices if devices is not None else jax.devices()
    return fanout.resolve_mesh_frames(cfg, devs)


# Distinct from None: shard resolution CAN resolve to None (single
# device), and the restart loop must not re-pay the probe for it.
_UNRESOLVED = object()


def _resolve_shard_frames(cfg: StreamConfig, devices
                          ) -> Optional[Tuple[int, int]]:
    """The RxC topology this run spatially shards over, or None: no jax
    import at all without ``--shard-frames``; else the shard resolver's
    verdict (explicit RxC under the routing threshold discipline, or
    the measured / feasibility-forced auto A/B)."""
    if cfg.shard_frames is None:
        return None
    import jax

    from tpu_stencil.stream import sharded as shardstream

    devs = devices if devices is not None else jax.devices()
    return shardstream.resolve_shard_frames(cfg, devs)


def _resolve_pipe_stages(cfg: StreamConfig, devices) -> int:
    """The temporal stage count this run pipelines over: 1 without
    ``--pipe-stages`` (no jax import at all on that path), else the
    pipeline resolver's verdict (explicit K under the composed device
    budget, or the roofline-gated measured auto A/B)."""
    if cfg.pipe_stages == 1:
        return 1
    import jax

    from tpu_stencil.parallel import pipeline as ppipe

    devs = devices if devices is not None else jax.devices()
    return ppipe.resolve_pipe_stages(cfg, devs)


def _close_io(own_source, source, own_sink, sink, failed: bool) -> None:
    """The mesh/shard-branch close discipline, in ONE place (the two
    branches used to carry verbatim copies): closing the source can
    race a reader parked in read() and the failure is already recorded
    first-wins, so a close-time error must never mask it; a sink-close
    error on an otherwise-clean run still raises (lost buffered frames
    are a real failure)."""
    if own_source:
        try:
            source.close()
        except OSError:
            pass
    if own_sink and sink is not None:
        try:
            sink.close()
        except OSError:
            if not failed:
                raise


def _run_stream_once(
    cfg: StreamConfig,
    devices: Optional[list] = None,
    resume: bool = False,
    source: Optional[frames_io.FrameSource] = None,
    sink: Optional[frames_io.FrameSink] = None,
    n_mesh: int = 1,
    shard: Optional[Tuple[int, int]] = None,
    pipe: int = 1,
) -> StreamResult:
    """One pipeline lifetime (see :func:`run_stream`, which owns the
    engine-restart loop around this). ``pipe`` > 1 — or a composed
    ``n_mesh`` > 1 with a resolved ``shard`` — routes the frame loop
    through the temporal-pipeline engine
    (:mod:`tpu_stencil.stream.pipelined`, the three-axis composer);
    otherwise ``n_mesh`` > 1 routes through the mesh fan-out engine
    (:mod:`tpu_stencil.parallel.fanout`) and a resolved ``shard`` =
    (R, C) through the spatially-sharded engine
    (:mod:`tpu_stencil.stream.sharded`) — resume/IO resolution, the
    restart ladder, and result assembly stay shared here, so the four
    engines can never drift on those contracts."""
    import jax

    from tpu_stencil.models.blur import IteratedConv2D

    obs.registry().counter("stream_jobs_total").inc()
    t_start = time.perf_counter()
    model = IteratedConv2D(cfg.filter_name, backend=cfg.backend,
                           schedule=cfg.schedule, boundary=cfg.boundary,
                           block_h=cfg.block_h, fuse=cfg.fuse)
    if devices is None:
        devices = jax.devices()
    composed = pipe > 1 or (n_mesh > 1 and shard is not None)
    if composed:
        # The full three-axis budget: groups x stages x spatial shard.
        r, c = shard if shard else (1, 1)
        devices = devices[: n_mesh * pipe * r * c]
    elif shard:
        devices = devices[: shard[0] * shard[1]]
    else:
        devices = devices[:n_mesh]
    # Report-what-ran for THIS run, on every path — a single-device run
    # after a mesh/sharded/pipelined one must not keep exposing stale
    # topology.
    obs.registry().gauge("stream_mesh_devices").set(n_mesh)
    obs.registry().gauge("stream_shard_devices").set(
        shard[0] * shard[1] if shard else 0
    )
    obs.registry().gauge("stream_pipe_stages").set(pipe if pipe > 1 else 0)

    start_frame = 0
    if resume:
        from tpu_stencil.runtime import checkpoint as ckpt

        restored = ckpt.restore_stream_progress(cfg, mesh_devices=n_mesh,
                                                shard_frames=shard,
                                                pipe_stages=pipe)
        if restored is not None:
            start_frame = restored
    elif cfg.checkpoint_every:
        # A non-resume run starts over: a stale sidecar from a killed
        # earlier run must be invalidated NOW, or a mid-stream engine
        # restart (run_stream's resume=True retry) before this run's
        # first commit would adopt the old progress and silently skip
        # frames this run never produced.
        from tpu_stencil.runtime import checkpoint as ckpt

        ckpt.clear_stream_progress(cfg)
    if cfg.frames is not None and start_frame > cfg.frames:
        raise ValueError(
            f"checkpoint records {start_frame} frames done but --frames "
            f"is {cfg.frames}"
        )
    out_spec = cfg.output_path if sink is None else "<injected>"
    if cfg.checkpoint_every and sink is None and (
        not frames_io.is_resumable_sink(out_spec)
    ):
        raise ValueError(
            f"--checkpoint-every needs a resumable sink (a file or "
            f"directory), not {out_spec!r}"
        )

    own_source = source is None
    own_sink = sink is None
    if own_source:
        source = frames_io.open_source(cfg.input, cfg.frame_bytes)
    try:
        if start_frame:
            source.skip(start_frame)
        if own_sink:
            sink = frames_io.open_sink(
                out_spec, cfg.frame_bytes, start_frame
            )
    except BaseException:
        if own_source:
            source.close()
        raise

    if composed:
        from tpu_stencil.stream import pipelined

        failed = False
        try:
            pres = pipelined.run_pipelined_stream(
                cfg, devices, n_mesh, pipe, shard, model, source, sink,
                start_frame,
            )
        except BaseException:
            failed = True
            raise
        finally:
            _close_io(own_source, source, own_sink, sink, failed)
        return _finish_result(
            cfg, resume, t_start, start_frame, pres["frames"],
            pres["stage_seconds"], pres["backend"], pres["schedule"],
            out_spec, n_devices=pres["n_devices"],
            per_device_frames=pres["per_device_frames"],
            shard_frames=shard, pipe_stages=pipe,
        )

    if shard is not None:
        from tpu_stencil.stream import sharded as shardstream

        failed = False
        try:
            sres = shardstream.run_shard_stream(
                cfg, devices, shard, model, source, sink, start_frame
            )
        except BaseException:
            failed = True
            raise
        finally:
            _close_io(own_source, source, own_sink, sink, failed)
        return _finish_result(
            cfg, resume, t_start, start_frame, sres["frames"],
            sres["stage_seconds"], sres["backend"], sres["schedule"],
            out_spec, n_devices=sres["n_devices"], shard_frames=shard,
        )

    if n_mesh > 1:
        from tpu_stencil.parallel import fanout

        failed = False
        try:
            mesh = fanout.run_mesh_frames(
                cfg, devices, n_mesh, model, source, sink, start_frame
            )
        except BaseException:
            failed = True
            raise
        finally:
            _close_io(own_source, source, own_sink, sink, failed)
        return _finish_result(
            cfg, resume, t_start, start_frame, mesh["frames"],
            mesh["stage_seconds"], mesh["backend"], mesh["schedule"],
            out_spec, n_devices=n_mesh,
            per_device_frames=mesh["per_device_frames"],
        )

    pl = _Pipeline(cfg)
    done = [start_frame]
    eng: dict = {}
    threads = [
        threading.Thread(target=_reader, args=(pl, source, start_frame),
                         name="stream-reader", daemon=True),
        threading.Thread(target=_drain, args=(pl, eng),
                         name="stream-drain", daemon=True),
        threading.Thread(target=_writer, args=(pl, sink, done),
                         name="stream-writer", daemon=True),
    ]
    try:
        for t in threads:
            t.start()
        _dispatch(pl, model, devices, eng)
        # Clean runs end via the sentinel cascade; failed runs via the
        # stop flag (queue waits unwind within their 50ms poll). One
        # stage can NOT unwind that way: a reader parked in a blocking
        # read() on a silent pipe — never wait on it indefinitely.
        for t in threads:
            while t.is_alive() and not pl.stop.is_set():
                t.join(timeout=0.1)
    finally:
        pl.stop.set()  # unstick any straggler stage before closing I/O
        for t in threads:
            t.join(timeout=1.0)
        pl.zero_gauge()  # aborted frames never pass release_window
        # The reader thread is a daemon either way; _close_io owns the
        # close-time error-masking rules.
        _close_io(own_source, source, own_sink, sink,
                  pl.failure is not None)

    if pl.failure is not None:
        stage, frame_index, cause = pl.failure
        raise StreamFailure(stage, frame_index, cause) from cause

    from tpu_stencil.models.blur import resolve_backend

    backend = eng.get("backend", resolve_backend(cfg.backend))
    return _finish_result(
        cfg, resume, t_start, start_frame, done[0] - start_frame,
        dict(pl.stage_seconds), backend, eng.get("schedule"), out_spec,
    )
