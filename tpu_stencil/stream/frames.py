"""Frame sources and sinks for the streaming engine.

The container contract is ``io/raw.py``'s, lifted to streams: a frame is
``H*W*C`` headerless bytes (trust-the-geometry — width/height/channels
are supplied out of band), and a *stream* is either

* one concatenated ``.raw`` stream — a regular file, a FIFO/pipe, or
  stdin/stdout (``"-"``); no header, no framing, EOF is the only
  terminator; or
* a directory of per-frame ``.raw`` files, consumed/produced in sorted
  name order (``frame_000000.raw`` ...).

Sources fill caller-owned staging buffers (``read_into`` — the engine's
ring reuses them, so steady state allocates nothing on the host) and
fail loudly on short reads: a stream that ends mid-frame is an error
with the frame index, never silent garbage (the same discipline
``io/raw.py`` applies to short files). A :class:`NullSink` discards
output for benchmarking the pipeline without a disk-write stage.
"""

from __future__ import annotations

import os
import stat as _stat
import sys
from typing import BinaryIO, List, Optional

import numpy as np

from tpu_stencil.io.raw import discard_stream_bytes, read_stream_into

FRAME_PATTERN = "frame_{:06d}.raw"


class FrameSource:
    """Sequential frame producer. Context-managed; single consumer."""

    def read_into(self, buf: np.ndarray) -> bool:
        """Fill ``buf`` (1-D uint8, one frame) with the next frame.
        Returns False on clean EOF (no bytes read); raises ``IOError``
        on a short read (stream ended mid-frame)."""
        raise NotImplementedError

    def skip(self, n: int) -> None:
        """Advance past ``n`` frames (resume support). Seekable sources
        seek; pipes read and discard."""
        raise NotImplementedError

    def mark(self):
        """A rewind point for transient-read retries: a zero-arg
        callable restoring the source to its current position, or None
        when the position cannot be restored (a pipe's consumed bytes
        are gone) — the engine only retries reads when a mark exists
        (:mod:`tpu_stencil.resilience.retry`)."""
        return None

    def close(self) -> None:
        pass

    def __enter__(self) -> "FrameSource":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class FrameSink:
    """In-order frame consumer. Context-managed; single producer. The
    engine guarantees ``write`` is called with strictly increasing
    frame indices starting at the resume point.

    ``retryable_writes``: True when ``write(index, frame)`` is
    idempotent (re-writing an index lands the same bytes in the same
    place — positioned file writes, per-frame directory files), so the
    engine may retry a transient write failure; append-only streams
    (stdout, pipes) are False — a retried partial write would duplicate
    bytes."""

    retryable_writes = False

    def write(self, index: int, frame: np.ndarray) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        """Durability point before a progress checkpoint commits."""
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "FrameSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RawStreamSource(FrameSource):
    """Concatenated headerless frames from one byte stream: a regular
    file, a FIFO/pipe path, or stdin (``"-"``). Regular files validate
    total size divisibility lazily (EOF mid-frame raises); pipes are
    pure sequential reads — the contract ``io/raw.py:read_raw_rows``
    applies to non-regular files."""

    def __init__(self, path: str, frame_bytes: int):
        self.path = path
        self.frame_bytes = frame_bytes
        self._frames_read = 0
        if path == "-":
            self._f: BinaryIO = sys.stdin.buffer
            self._owns = False
        else:
            self._f = open(path, "rb", buffering=0)
            self._owns = True

    def read_into(self, buf: np.ndarray) -> bool:
        view = memoryview(buf).cast("B")
        assert len(view) == self.frame_bytes
        got = read_stream_into(self._f, view)
        if got == 0:
            return False
        if got < self.frame_bytes:
            raise IOError(
                f"{self.path}: stream ended mid-frame "
                f"(frame {self._frames_read}: {got}/{self.frame_bytes} bytes)"
            )
        self._frames_read += 1
        return True

    def skip(self, n: int) -> None:
        if n <= 0:
            return
        nbytes = n * self.frame_bytes
        if self._f.seekable():
            self._f.seek(nbytes, os.SEEK_CUR)
        else:
            discard_stream_bytes(
                self._f, nbytes, f"{self.path} (skipping {n} resumed frames)"
            )
        self._frames_read += n

    def mark(self):
        if not self._f.seekable():
            return None  # a pipe's consumed bytes cannot be re-read
        pos = self._f.tell()
        frames = self._frames_read

        def restore() -> None:
            self._f.seek(pos)
            self._frames_read = frames

        return restore

    def close(self) -> None:
        if self._owns:
            self._f.close()


class RawDirectorySource(FrameSource):
    """A sorted directory of per-frame ``.raw`` files. Each file must be
    exactly one frame; a wrong-sized file fails loudly with its name
    (the directory analog of the short-read contract)."""

    def __init__(self, path: str, frame_bytes: int):
        self.path = path
        self.frame_bytes = frame_bytes
        self._names: List[str] = sorted(
            n for n in os.listdir(path) if n.endswith(".raw")
        )
        self._i = 0

    def __len__(self) -> int:
        return len(self._names)

    def read_into(self, buf: np.ndarray) -> bool:
        if self._i >= len(self._names):
            return False
        name = os.path.join(self.path, self._names[self._i])
        size = os.path.getsize(name)
        if size != self.frame_bytes:
            raise IOError(
                f"{name}: frame file holds {size} bytes, "
                f"expected {self.frame_bytes}"
            )
        view = memoryview(buf).cast("B")
        with open(name, "rb", buffering=0) as f:
            got = read_stream_into(f, view)
        if got != self.frame_bytes:
            raise IOError(f"{name}: short read {got}/{self.frame_bytes}")
        self._i += 1
        return True

    def skip(self, n: int) -> None:
        self._i += max(0, n)

    def mark(self):
        i = self._i

        def restore() -> None:
            self._i = i

        return restore


class RawStreamSink(FrameSink):
    """Concatenated headerless frames to one byte stream: a regular
    file, a FIFO/pipe path, or stdout (``"-"``). ``start_frame``
    (resume) positions a regular file at the resume offset; pipes
    cannot resume mid-stream and refuse."""

    def __init__(self, path: str, frame_bytes: int, start_frame: int = 0):
        self.path = path
        self.frame_bytes = frame_bytes
        if path == "-":
            self._f: BinaryIO = sys.stdout.buffer
            self._owns = False
            if start_frame:
                raise ValueError("cannot resume a stream into stdout")
        else:
            exists = os.path.exists(path)
            if start_frame and not exists:
                raise ValueError(
                    f"cannot resume: sink {path} does not exist"
                )
            self._f = open(path, "r+b" if (start_frame and exists) else "wb")
            self._owns = True
            if start_frame:
                if not self._f.seekable():
                    self._f.close()
                    raise ValueError(
                        f"cannot resume a stream into non-seekable {path}"
                    )
                self._f.seek(start_frame * frame_bytes)
                self._f.truncate()
        # Positioned writes on seekable files make write(index, ...)
        # idempotent — frame i's home is exactly i*frame_bytes — so a
        # transient failure can be retried without duplicating bytes.
        # Pipes stay append-only and non-retryable, and stdout is
        # excluded unconditionally (a capture harness can make it
        # claim seekability it must not be trusted with).
        self.retryable_writes = self._owns and self._f.seekable()

    def write(self, index: int, frame: np.ndarray) -> None:
        if self.retryable_writes:
            self._f.seek(index * self.frame_bytes)
        # Buffer-protocol write: ascontiguousarray is a no-op view for
        # the already-contiguous uint8 arrays the engine drains, so a
        # frame is NOT copied again on its way out (tobytes() would
        # memcpy every frame inside the stage that bounds a write-bound
        # stream's throughput).
        arr = np.ascontiguousarray(frame, dtype=np.uint8)
        self._f.write(memoryview(arr).cast("B"))

    def flush(self) -> None:
        """Durability point (a progress checkpoint is about to commit):
        flush AND fsync owned regular files — a checkpoint recording
        "frames [0, n) are durable" must not be ordered ahead of the
        frames themselves in the page cache. Pipes/stdout only flush
        (fsync is meaningless there, and their sinks are not resumable
        anyway)."""
        self._f.flush()
        if self._owns:
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass  # non-regular sink (FIFO): flush is all there is

    def close(self) -> None:
        self.flush()
        if self._owns:
            self._f.close()


class RawDirectorySink(FrameSink):
    """One ``frame_%06d.raw`` file per frame, atomic per frame: bytes
    land in a tmp file and ``os.replace`` publishes the final name
    (the ``runtime/checkpoint.py`` discipline), so a crash mid-write
    can never leave a torn frame under a complete-looking name. Resume
    is natural — frame files are keyed by index, rewrites idempotent."""

    retryable_writes = True  # per-index atomic files: rewrites idempotent

    def __init__(self, path: str, frame_bytes: int, start_frame: int = 0):
        self.path = path
        self.frame_bytes = frame_bytes
        os.makedirs(path, exist_ok=True)

    def write(self, index: int, frame: np.ndarray) -> None:
        name = os.path.join(self.path, FRAME_PATTERN.format(index))
        arr = np.ascontiguousarray(frame, dtype=np.uint8)
        tmp = name + ".tmp"
        with open(tmp, "wb") as f:
            f.write(memoryview(arr).cast("B"))
            # fsync BEFORE the rename: without it a power cut can
            # publish the name over still-dirty data — a torn frame
            # under a complete-looking name, the exact hole the atomic
            # publish exists to close.
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, name)


class NullSink(FrameSink):
    """Discard frames — benchmark the pipeline without a write stage."""

    retryable_writes = True  # discarding is trivially idempotent

    def __init__(self, *a, **k):
        self.frames_written = 0

    def write(self, index: int, frame: np.ndarray) -> None:
        self.frames_written += 1


class TileScatter:
    """Shard-scatter staging views for the spatially-sharded stream
    (:mod:`tpu_stencil.stream.sharded`): one reusable host staging tile
    per mesh position, plus the precomputed copy plan that scatters a
    flat frame buffer into them.

    The tiles are the H2D unit — each is ``device_put`` onto its own
    device, so uploads split per shard and frame ``i+1``'s tiles can
    overlap frame ``i``'s exchange-and-compute. Pad regions (the grid's
    ceil-divide overhang at the bottom/right image edge) are zeroed
    ONCE at construction and never written again: the scatter only
    copies the image-interior window of each tile, so steady state
    allocates nothing and re-zeroes nothing (the staging-ring
    discipline, per shard). Pure numpy — jax-free, like every container
    here; the device placement lives with the engine.

    ``specs``: one ``(rows, cols)`` pair of ``slice`` objects per tile,
    each a window into the PADDED global canvas (the engine derives
    them from the mesh sharding's index map, so the scatter layout can
    never drift from what the compiled program expects)."""

    def __init__(self, frame_shape, specs) -> None:
        self.frame_shape = tuple(frame_shape)
        h, w = self.frame_shape[:2]
        trailing = self.frame_shape[2:]
        self.specs = list(specs)
        self.tiles: List[np.ndarray] = []
        self._copies = []  # (tile_idx, tile_window, frame_window)
        for i, (rows, cols) in enumerate(self.specs):
            th = rows.stop - rows.start
            tw = cols.stop - cols.start
            self.tiles.append(np.zeros((th, tw) + trailing, np.uint8))
            # The image-interior window of this tile (empty for tiles
            # fully inside the pad overhang — nothing to copy, the
            # zeros already there ARE the pad semantics).
            r1 = min(rows.stop, h)
            c1 = min(cols.stop, w)
            if r1 > rows.start and c1 > cols.start:
                self._copies.append((
                    i,
                    (slice(0, r1 - rows.start), slice(0, c1 - cols.start)),
                    (slice(rows.start, r1), slice(cols.start, c1)),
                ))

    def scatter(self, buf: np.ndarray) -> List[np.ndarray]:
        """Copy one flat frame buffer into the staging tiles and return
        them (the same arrays every call — callers must consume each
        tile, e.g. via a fenced H2D, before the next scatter)."""
        frame = buf.reshape(self.frame_shape)
        for i, tile_win, frame_win in self._copies:
            self.tiles[i][tile_win] = frame[frame_win]
        return self.tiles

    def gather_into(self, out: np.ndarray, shards) -> np.ndarray:
        """The D2H inverse: crop each per-shard result back into the
        true-image window of ``out`` (pad rows/cols dropped). ``shards``
        iterates ``(tile_index, array)`` in any order."""
        h, w = self.frame_shape[:2]
        for i, arr in shards:
            rows, cols = self.specs[i]
            r1 = min(rows.stop, h)
            c1 = min(cols.stop, w)
            if r1 > rows.start and c1 > cols.start:
                out[rows.start:r1, cols.start:c1] = np.asarray(arr)[
                    : r1 - rows.start, : c1 - cols.start
                ]
        return out


def _is_dir_spec(spec: str) -> bool:
    return spec.endswith(os.sep) or os.path.isdir(spec)


def open_source(spec: str, frame_bytes: int) -> FrameSource:
    """Resolve a source spec: ``"-"`` = stdin, an existing directory =
    sorted per-frame files, anything else = one concatenated byte
    stream (regular file or FIFO — non-regular paths are read purely
    sequentially)."""
    if spec != "-" and _is_dir_spec(spec):
        return RawDirectorySource(spec.rstrip(os.sep), frame_bytes)
    return RawStreamSource(spec, frame_bytes)


def open_sink(spec: str, frame_bytes: int, start_frame: int = 0) -> FrameSink:
    """Resolve a sink spec: ``"null"`` = discard, ``"-"`` = stdout, a
    directory (existing, or a trailing-separator path) = per-frame
    files, anything else = one concatenated stream file/pipe."""
    if spec == "null":
        return NullSink()
    if spec != "-" and _is_dir_spec(spec):
        return RawDirectorySink(spec.rstrip(os.sep), frame_bytes, start_frame)
    return RawStreamSink(spec, frame_bytes, start_frame)


def is_restartable_source(spec: str) -> bool:
    """True when a fresh ``open_source`` of ``spec`` can re-serve frames
    an earlier open already consumed (a regular file seeks, a frame
    directory re-lists) — the gate on the engine's mid-stream restart:
    a pipe/FIFO/stdin's consumed frames are gone, so restarting one
    would silently drop them."""
    if spec == "-":
        return False
    if _is_dir_spec(spec):
        return True
    return os.path.exists(spec) and _stat.S_ISREG(os.stat(spec).st_mode)


def is_resumable_sink(spec: str) -> bool:
    """True when progress into this sink survives a restart (a real
    filesystem artifact): checkpointing into 'null', stdout, or a FIFO
    would record progress no one can resume from."""
    if spec in ("null", "-"):
        return False
    if _is_dir_spec(spec):
        return True
    if os.path.exists(spec):
        return _stat.S_ISREG(os.stat(spec).st_mode)
    return True  # a not-yet-created regular stream file
