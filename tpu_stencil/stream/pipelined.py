"""Pipelined frame streaming: the three-axis composed engine.

This module drives :class:`tpu_stencil.parallel.pipeline.PipelineRunner`
from the stream: frames flow systolically through K temporal rep-stages
over ICI inside ONE persistent ``shard_map`` program — no host
round-trip between stages — and composes all three placement axes in a
single run:

* **frame lanes** (``--mesh-frames G``): G independent pipeline groups,
  frames dealt round-robin (the fan-out deal, frame ``i`` -> group
  ``(i - start) % G``), merged in order by one writer;
* **temporal stages** (``--pipe-stages K``): each group's rep loop is
  split into K contiguous stage slices, one resident frame per stage,
  one ``lax.ppermute`` hand-off per tick;
* **spatial shards** (``--shard-frames RxC``): each stage is an RxC
  spatial mesh running the shared local step (halo exchange inside the
  loop body).

One group consumes ``K * R * C`` devices; the run consumes
``G * K * R * C``. ``K == 1`` with ``G > 1`` and ``R*C > 1`` is the
fan-of-sharded-groups composition PR 15 left open — here it is just the
degenerate pipeline (no fill, immediate flush).

Shape of the machine (docs/STREAMING.md "Temporal pipeline"):

* **one reader thread** — the fan-out reader verbatim
  (:func:`tpu_stencil.parallel.fanout._reader`): round-robin onto
  per-group lanes, CRC at ingest, witness sampling, chaos site.
* **per-group dispatch thread** — owns the fill/drain bookkeeping:
  scatter the staged frame into stage-0 spatial tiles (pad zeroed
  once), fenced per-tile H2D, assemble the 3-axis global input (every
  non-stage-0 device rides a cached committed zero tile — no per-tick
  H2D for them), run one tick. A deque of pending frame indices maps
  ticks to emerging frames: the frame fed at tick ``t`` emerges at tick
  ``t + K - 1``, so the oldest pending frame is flushed to the drain
  once ``ticks >= K``, and after EOF the dispatcher runs zero-input
  drain ticks until the deque empties — short streams (F < K) still
  produce every frame, bit-exact.
* **per-group drain thread** — fences the tick in dispatch order
  (watchdogged), then copies back ONLY the last stage's shards (each
  frame's finished result) with per-shard ``d2h`` spans, cropping the
  pad off into the output frame.
* **one writer thread** — the fan-out writer with a ``save_progress``
  closure stamping the FULL three-axis topology into the checkpoint
  sidecar, so a ``--resume`` under any different (G, K, RxC) fails
  typed instead of silently mis-weaving the deal.

Failure semantics, fault sites, stage spans/clocks and the
engine-restart ladder are the engines' shared vocabulary
(:mod:`tpu_stencil.stream.engine` owns the restart loop). Every path is
bit-exact against the golden model: the per-stage rep counts partition
``reps`` exactly and every stage runs the identical local step
(``tests/test_pipeline.py`` fuzzes fill/drain edges — F < K, F == K,
reps % K != 0 — against per-frame golden results).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

from tpu_stencil import obs
from tpu_stencil.config import StreamConfig
from tpu_stencil.integrity import checksum as _checksum
from tpu_stencil.integrity import witness as _witness_mod
from tpu_stencil.resilience import deadline as _deadline
from tpu_stencil.resilience import faults as _faults
from tpu_stencil.stream import frames as frames_io
# Module-level by design, like parallel/fanout.py and stream/sharded.py:
# stream.engine only imports this module lazily inside run_stream, so
# there is no cycle, and all engines share one _Abort/_StageSpan/
# StreamFailure vocabulary.
from tpu_stencil.stream import engine as _sengine
from tpu_stencil.parallel import fanout as _fanout

# The lanes/reader/writer are the fan-out machinery verbatim — one
# deal, one merge, one EOF protocol across both multi-lane engines.
_EOF = _fanout._EOF
_Control = _fanout._Control
_Lane = _fanout._Lane


class _GroupPlumbing:
    """One pipeline group's device-side state: the cached runner, the
    stage-0 scatter layout derived from the RUNNER'S OWN sharding (the
    staging views can never drift from what the compiled program
    expects), and the last-stage gather map."""

    def __init__(self, cfg: StreamConfig, runner) -> None:
        self.runner = runner
        gshape = runner.global_shape
        imap = runner.sharding.devices_indices_map(gshape)
        # Spatial tile specs, in stage-0 flat order. dims 1/2 of the
        # (K, Hp, Wp[, C]) global are the padded spatial plane; every
        # stage slice shares the SAME spatial layout, so these specs
        # serve both the stage-0 scatter and the last-stage gather.
        specs = []
        for dev in runner.stage0_devices:
            idx = imap[dev]
            rows = slice(*idx[1].indices(gshape[1])[:2])
            cols = slice(*idx[2].indices(gshape[2])[:2])
            specs.append((rows, cols))
        self.scatter = frames_io.TileScatter(cfg.frame_shape, specs)
        self.stage0 = list(runner.stage0_devices)
        self.last_to_tile = {
            d.id: i for i, d in enumerate(runner.last_devices)
        }


def _dispatch(ctrl: _Control, cfg: StreamConfig, lane: _Lane,
              pb: _GroupPlumbing, g: int) -> None:
    """One group's tick loop, owning the fill/drain state machine.

    ``pending`` holds the fed-but-not-yet-emerged frame indices in feed
    order; its head is exactly the frame the current tick's last-stage
    output contains once ``ticks >= K``. After the lane's EOF, drain
    ticks feed the cached zero input until ``pending`` empties — the
    explicit flush that makes F < K streams complete."""
    import jax

    runner = pb.runner
    k = runner.stages
    nsp = len(pb.stage0)
    idx, stage = -1, "compute"  # bootstrap failures are compile/compute
    fault_h2d = _faults.site("h2d")
    fault_compute = _faults.site("compute")
    try:
        # Warm-up: the persistent tick's compile lands before the first
        # real frame (reps is a traced scalar, so the zero-frame
        # program IS the production program); its returned carry is the
        # stream's initial fill state.
        carry = runner.warm(cfg.repetitions)
        zero = runner.zero_input()
        pending: deque = deque()
        ticks = 0
        while True:
            item = ctrl.get(lane.filled_q)
            if item is _EOF:
                break
            idx, bi, crc, wit = item
            stage = "h2d"
            if fault_h2d is not None:
                fault_h2d(idx)
            # The shared H2D-boundary re-verification (ring slot), then
            # per staged tile — ingest integrity per shard.
            _sengine._verify_staged(lane.ring[bi], crc, idx)
            tiles = pb.scatter.scatter(lane.ring[bi])
            lane.free_q.put(bi)  # scatter consumed the ring slot
            tile_crcs = (
                [_checksum.crc32c(t) for t in tiles]
                if cfg.verify_ingest else [None] * len(tiles)
            )
            stage0_map = {}
            for d, (tile, dev) in enumerate(zip(tiles, pb.stage0)):
                _sengine._verify_staged(tile, tile_crcs[d], idx)
                with ctrl.stage("h2d", idx, dev=g * nsp + d) as s:
                    # Fenced per tile: the span holds only THIS tile's
                    # PCIe copy; the pipeline keeps ticking. The [None]
                    # view adds the unit stages dim of the local shape —
                    # and MUST be snapshotted: device_put zero-copy
                    # aliases host views on the CPU backend, and the
                    # scatter reuses this staging tile on the next
                    # frame, which would rewrite an in-flight tick's
                    # input under it.
                    stage0_map[dev.id] = s.fence(
                        jax.device_put(np.array(tile[None]), dev)
                    )
            inp = runner.assemble_input(stage0_map)
            stage = "compute"
            if fault_compute is not None:
                fault_compute(idx)
            t_disp = time.perf_counter()
            carry, out = runner.tick(carry, inp, cfg.repetitions)
            pending.append((idx, wit, t_disp))
            ticks += 1
            if ticks >= k:
                fidx, fwit, ft = pending.popleft()
                ctrl.put(lane.inflight_q, (fidx, out, ft, fwit))
        # EOF: drain ticks on zero input until every fed frame has
        # emerged from the last stage (K - 1 ticks on a long stream;
        # up to K - 1 + fed on a short one — same loop either way).
        stage = "compute"
        while pending:
            carry, out = runner.tick(carry, zero, cfg.repetitions)
            ticks += 1
            if ticks >= k:
                fidx, fwit, ft = pending.popleft()
                ctrl.put(lane.inflight_q, (fidx, out, ft, fwit))
        ctrl.put(lane.inflight_q, _EOF)
    except _sengine._Abort:
        pass
    except BaseException as e:
        ctrl.fail(stage, max(idx, 0), e)


def _drainer(ctrl: _Control, cfg: StreamConfig, lane: _Lane,
             pb: _GroupPlumbing, g: int,
             meter: "_fanout._InflightMeter") -> None:
    """Fence one group's tick in dispatch order (watchdogged), copy
    back ONLY the last stage's shards — each frame's finished result —
    crop the pad off, hand off to the writer's merge."""
    idx, stage = -1, "compute"
    fault_d2h = _faults.site("d2h")
    fault_corrupt = _faults.site("integrity.corrupt_result")
    timeout_s = _deadline.resolve(cfg.dispatch_timeout_s)
    try:
        while True:
            item = ctrl.get(lane.inflight_q)
            if item is _EOF:
                ctrl.put(lane.done_q, _EOF)
                return
            idx, out_dev, t_disp, wit = item
            stage = "compute"
            with ctrl.stage("compute", idx, t0=t_disp, dev=g):
                _deadline.fence(
                    out_dev, timeout_s,
                    f"stream.compute[frame={idx},pipe-group={g}]",
                )
            stage = "d2h"
            frame = np.empty(cfg.frame_shape, np.uint8)
            for shard in out_dev.addressable_shards:
                d = pb.last_to_tile.get(shard.device.id)
                if d is None:
                    continue  # not a last-stage shard: still in flight
                with ctrl.stage("d2h", idx, dev=g * len(pb.stage0) + d):
                    if fault_d2h is not None:
                        fault_d2h(idx)
                    piece = np.asarray(shard.data)
                pb.scatter.gather_into(frame, [(d, piece[0])])
            if fault_corrupt is not None and _checksum.fired(
                    fault_corrupt, idx):
                _checksum.corrupt_array(frame)
            meter.dec()
            ctrl.put(lane.done_q, (idx, frame, wit))
    except _sengine._Abort:
        pass
    except BaseException as e:
        ctrl.fail(stage, max(idx, 0), e)


def run_pipelined_stream(cfg: StreamConfig, devices, groups: int,
                         stages: int, shard: Optional[Tuple[int, int]],
                         model, source, sink, start_frame: int) -> dict:
    """One pipelined-stream lifetime over the composed
    (``groups`` x ``stages`` x RxC) topology. The caller
    (:func:`tpu_stencil.stream.engine._run_stream_once`) owns
    source/sink lifecycle, resume resolution and result assembly; this
    returns ``{"frames", "stage_seconds", "per_device_frames",
    "backend", "schedule", "n_devices"}`` or raises
    :class:`~tpu_stencil.stream.engine.StreamFailure`. Each group's
    persistent tick program comes from the PROCESS-SHARED runner cache
    (:func:`tpu_stencil.parallel.pipeline.shared_pipeline_runner`) —
    groups over identical shapes share one trace, and repeat runs never
    recompile."""
    from tpu_stencil.parallel import pipeline as _ppipe

    r, c = shard if shard else (1, 1)
    per_group = stages * r * c
    need = groups * per_group
    devices = list(devices)
    if len(devices) < need:
        raise ValueError(
            f"pipelined topology {groups} group(s) x {stages} stage(s) "
            f"x {r}x{c} shard needs {need} devices, have {len(devices)}"
        )
    plumbing: List[_GroupPlumbing] = []
    for g in range(groups):
        runner = _ppipe.shared_pipeline_runner(
            model, (cfg.height, cfg.width), cfg.channels, stages,
            shard_shape=(r, c),
            devices=devices[g * per_group: (g + 1) * per_group],
            registry=obs.registry(),
        )
        if runner is None:
            # An explicitly requested topology the mesh cannot serve
            # fails loudly, naming the constraint — no silent fallback
            # mid-stream (the run_shard_stream discipline).
            raise ValueError(
                f"--pipe-stages {stages} with shard {r}x{c} cannot "
                f"serve a {cfg.height}x{cfg.width} frame: the "
                f"per-device tile is smaller than the filter halo (or "
                f"the boundary refuses padding); use a smaller shard "
                f"grid or a larger frame"
            )
        plumbing.append(_GroupPlumbing(cfg, runner))
    ctrl = _Control()
    lanes = [_Lane(cfg) for _ in range(groups)]
    done = [start_frame]
    meter = _fanout._InflightMeter()
    witness = (
        _witness_mod.WitnessSampler(cfg.witness_rate,
                                    seed=cfg.witness_seed)
        if (cfg.witness_rate > 0
            and cfg.repetitions <= _witness_mod.WITNESS_MAX_REPS)
        else None
    )

    def save_progress(frames_done: int) -> None:
        from tpu_stencil.runtime import checkpoint as ckpt

        ckpt.save_stream_progress(
            cfg, frames_done, mesh_devices=groups,
            cursors=(_fanout.device_cursors(frames_done, start_frame,
                                            groups)
                     if groups > 1 else None),
            shard_frames=shard, pipe_stages=stages,
        )

    threads = [
        threading.Thread(
            target=_fanout._reader,
            args=(ctrl, cfg, source, lanes, start_frame, meter, witness),
            name="pipelined-reader", daemon=True,
        ),
        threading.Thread(
            target=_fanout._writer,
            args=(ctrl, cfg, sink, lanes, start_frame, done,
                  save_progress),
            name="pipelined-writer", daemon=True,
        ),
    ]
    for g, (lane, pb) in enumerate(zip(lanes, plumbing)):
        threads.append(threading.Thread(
            target=_dispatch, args=(ctrl, cfg, lane, pb, g),
            name=f"pipelined-dispatch-{g}", daemon=True,
        ))
        threads.append(threading.Thread(
            target=_drainer, args=(ctrl, cfg, lane, pb, g, meter),
            name=f"pipelined-drain-{g}", daemon=True,
        ))
    try:
        for t in threads:
            t.start()
        # Clean runs end via the sentinel cascade; failed runs via the
        # stop flag. Like the other engines, never wait indefinitely on
        # a reader parked in a blocking pipe read.
        for t in threads:
            while t.is_alive() and not ctrl.stop.is_set():
                t.join(timeout=0.1)
    finally:
        ctrl.stop.set()
        for t in threads:
            t.join(timeout=1.0)
        meter.zero()  # aborted in-flight frames never pass dec()
    if ctrl.failure is not None:
        stage, frame_index, cause = ctrl.failure
        raise _sengine.StreamFailure(stage, frame_index, cause) from cause
    runner0 = plumbing[0].runner
    return {
        "frames": done[0] - start_frame,
        "stage_seconds": dict(ctrl.stage_seconds),
        "per_device_frames": [lane.frames for lane in lanes],
        "backend": runner0.backend,
        "schedule": runner0.schedule,
        "n_devices": need,
    }
