"""Spatially sharded frames inside the stream (``--shard-frames RxC``).

The third composition of the engines: ``--mesh-frames`` (PR 9) fans
WHOLE frames over per-device lanes — one device must still hold one
frame — and serve's oversized-request route (PR 9) spatially shards one
REQUEST over the mesh. This module composes the stream pipeline (PR 5)
with that sharded route so each in-flight frame shards over the local
mesh: the workload class the stack previously refused — a frame larger
than one device's HBM — streams to completion, bit-exact (the
reference's MPI variant exists for exactly this reason: one worker
cannot hold the whole image).

Shape of the machine (docs/STREAMING.md "Spatially sharded frames"):

* **reader thread** — the single-device engine's, verbatim
  (:func:`tpu_stencil.stream.engine._reader`): whole frames into the
  staging ring, CRC'd at ingest, witness-sampled, window-gated.
* **dispatch (main thread)** — scatters each staged frame into
  reusable per-shard host tiles
  (:class:`tpu_stencil.stream.frames.TileScatter` — pad regions zeroed
  once, steady state copies only image-interior windows), re-verifies
  the ring slot AND each staged tile against its CRC (ingest integrity
  per shard), uploads each tile to its own device with a fenced
  per-shard ``stream.h2d`` span (``dev=`` tagged — the H2D stage is
  split per shard, so frame ``i+1``'s tile uploads overlap frame
  ``i``'s exchange-and-compute), assembles the global sharded array
  (``jax.make_array_from_single_device_arrays``) and launches the
  cached mesh program.
* **the mesh program** — a :class:`tpu_stencil.parallel.sharded
  .ShardedRunner` resolved through the PROCESS-SHARED runner cache
  (:func:`tpu_stencil.parallel.sharded.shared_runner`) under the same
  ``shard_min_pixels`` routing discipline as serve's oversized-request
  path — stream and serve never compile the same mesh program twice.
  The default ``--overlap edge`` threads the per-edge persistent
  double-buffered exchange (``edge_iterate``, arXiv:2508.13370's
  partitioned/persistent pattern) through the rep-loop carry.
* **drain thread** — fences compute in dispatch order (watchdogged),
  copies each shard back with a per-shard ``stream.d2h`` span, crops
  the pad off into the output frame.
* **writer thread** — the single-device engine's, with the progress
  sidecar committing the RxC shard topology
  (:func:`tpu_stencil.runtime.checkpoint.save_stream_progress`), so a
  ``--resume`` under a different topology fails typed
  (:class:`~tpu_stencil.runtime.checkpoint.MeshCursorMismatch`)
  instead of silently mis-scattering.

``--shard-frames 0`` (auto) decides by a measured single-vs-sharded
A/B (:func:`measure_shard_ab`) under the never-enable-a-measured-loss
discipline — except when the frame exceeds the per-device HBM
feasibility bound (:func:`tpu_stencil.runtime.roofline
.hbm_frame_feasible`), where sharding is the only arm that can run and
no probe is paid. Real-probe verdicts persist in the autotune cache
(``cached_stream_verdict``), so a warm cache re-decides with zero
probe frames.

Failure semantics, fault sites, stage spans/clocks and the
engine-restart ladder are the single-device engine's
(:mod:`tpu_stencil.stream.engine` owns the restart loop; a restart
re-shards at the SAME resolved topology, so the checkpoint's recorded
RxC stays aligned). Every path is bit-exact against the golden model:
sharding changes only WHERE a frame's pixels compute, never what.
"""

from __future__ import annotations

import dataclasses
import sys
import time
from typing import Callable, Optional, Tuple

import numpy as np

from tpu_stencil import obs
from tpu_stencil.config import StreamConfig
from tpu_stencil.integrity import checksum as _checksum
from tpu_stencil.resilience import deadline as _deadline
from tpu_stencil.resilience import faults as _faults
from tpu_stencil.stream import frames as frames_io
# Module-level by design, like parallel/fanout.py: stream.engine only
# imports this module lazily inside run_stream, so there is no cycle,
# and the engines share one _Abort/_StageSpan/StreamFailure vocabulary.
from tpu_stencil.stream import engine as _sengine

_EOF = _sengine._EOF

# Frames per arm of the auto (--shard-frames 0) measured A/B probe.
PROBE_FRAMES = 3


def resolve_shard_frames(cfg: StreamConfig, devices,
                         measure: Optional[Callable] = None
                         ) -> Optional[Tuple[int, int]]:
    """Resolve ``cfg.shard_frames`` to the RxC topology that actually
    runs, or None (single-device — report-what-ran, like every auto
    knob). The routing discipline is serve's oversized-request one: a
    frame below ``shard_min_pixels`` stays single-device even under an
    explicit RxC (the per-device tiles would be too small for the
    exchange to pay for itself). An explicit RxC above the threshold is
    honored (failing loudly when fewer than R*C devices exist, naming
    both counts); ``(0, 0)`` (auto) shards WITHOUT a probe when the
    frame exceeds the per-device HBM feasibility bound (the
    single-device arm cannot run), else runs the measured A/B
    (:func:`measure_shard_ab`, or the injected ``measure``) and enables
    sharding only when strictly faster. Real-probe verdicts persist in
    the autotune cache; injected measures bypass it in both
    directions."""
    if cfg.shard_frames is None:
        return None
    if cfg.width * cfg.height < cfg.shard_min_pixels:
        print(
            f"stream: --shard-frames: {cfg.width}x{cfg.height} frame is "
            f"below the routing threshold ({cfg.shard_min_pixels} px) "
            f"-> single-device",
            file=sys.stderr, flush=True,
        )
        return None
    n_avail = len(devices)
    if cfg.shard_frames != (0, 0):
        r, c = cfg.shard_frames
        if r * c > n_avail:
            raise ValueError(
                f"--shard-frames {r}x{c} asks for {r * c} devices, "
                f"have {n_avail}"
            )
        return (r, c)
    # auto (0): nothing to shard over on one device.
    if n_avail < 2:
        return None
    from tpu_stencil.parallel import partition
    from tpu_stencil.runtime import autotune, roofline

    mesh_shape = tuple(partition.grid_shape(
        n_avail, cfg.height, cfg.width
    ))
    if not roofline.hbm_frame_feasible(cfg.frame_bytes,
                                       cfg.pipeline_depth):
        # The single-device arm cannot run at all: shard, no probe.
        print(
            f"stream: --shard-frames auto: frame working set exceeds "
            f"the per-device HBM feasibility bound "
            f"({roofline.device_hbm_bytes()} bytes) -> shard "
            f"{mesh_shape[0]}x{mesh_shape[1]} (no probe — the "
            f"single-device arm is infeasible)",
            file=sys.stderr, flush=True,
        )
        return mesh_shape
    geometry = (cfg.height, cfg.width, cfg.channels)
    topo = f"mesh{mesh_shape[0]}x{mesh_shape[1]}"
    token = autotune.stream_cfg_token(cfg)
    if measure is None:
        hit = autotune.cached_stream_verdict(
            "shardstream", geometry, cfg.repetitions,
            cfg.pipeline_depth, topo, token,
        )
        if hit is not None and (
            hit["pick"] == 0
            or (isinstance(hit["pick"], list) and len(hit["pick"]) == 2
                and hit["pick"][0] * hit["pick"][1] <= n_avail)
        ):
            pick = (
                None if hit["pick"] == 0 else tuple(hit["pick"])
            )
            print(
                f"stream: --shard-frames auto verdict from warm cache "
                f"-> {'shard ' + topo[4:] if pick else 'single-device'}"
                f" (zero probe frames)",
                file=sys.stderr, flush=True,
            )
            return pick
    t_single, t_shard = (measure or measure_shard_ab)(
        cfg, devices, mesh_shape
    )
    pick = mesh_shape if t_shard < t_single else None
    if measure is None:
        autotune.store_stream_verdict(
            "shardstream", geometry, cfg.repetitions,
            cfg.pipeline_depth, topo,
            {"pick": list(pick) if pick else 0,
             "single_us": round(t_single * 1e6, 2),
             "shard_us": round(t_shard * 1e6, 2)},
            token,
        )
    print(
        f"stream: --shard-frames auto measured single={t_single:.3f}s "
        f"shard[{mesh_shape[0]}x{mesh_shape[1]}]={t_shard:.3f}s -> "
        f"{'shard ' + topo[4:] if pick else 'single-device'}",
        file=sys.stderr, flush=True,
    )
    return pick


def measure_shard_ab(cfg: StreamConfig, devices,
                     mesh_shape: Tuple[int, int],
                     frames: int = PROBE_FRAMES
                     ) -> Tuple[float, float]:
    """The measured single-vs-sharded A/B behind ``--shard-frames 0``
    (auto): run a tiny synthetic stream (random frames, null sink) once
    warm + once timed at ``cfg.pipeline_depth`` on one device and
    spatially sharded over ``mesh_shape``. Returns ``(single_seconds,
    shard_seconds)``. The probe pays ~2 compiles + ``4 * frames *
    reps`` of compute — the documented cost of a measured verdict; its
    counters/spans run under a scratch registry so they never inflate
    the caller's own run (the :func:`~tpu_stencil.parallel.fanout
    .measure_fanout_ab` discipline)."""
    rng = np.random.default_rng(0)
    frame = rng.integers(0, 256, cfg.frame_bytes, dtype=np.uint8)

    class _Synth(frames_io.FrameSource):
        def __init__(self, k: int) -> None:
            self._left = k

        def read_into(self, buf) -> bool:
            if self._left <= 0:
                return False
            np.copyto(buf, frame)
            self._left -= 1
            return True

    def one(shard) -> float:
        pcfg = dataclasses.replace(
            cfg, frames=frames, shard_frames=shard, shard_min_pixels=1,
            output="null", checkpoint_every=0, progress_every=0,
        )
        _sengine.run_stream(pcfg, devices=list(devices),
                            source=_Synth(frames),
                            sink=frames_io.NullSink())  # warm: compiles land
        t0 = time.perf_counter()
        _sengine.run_stream(pcfg, devices=list(devices),
                            source=_Synth(frames),
                            sink=frames_io.NullSink())
        return time.perf_counter() - t0

    with obs.scratch_registry():
        return one(None), one(tuple(mesh_shape))


class _ShardPlumbing:
    """The per-run device-side state of one sharded stream: the cached
    runner, the scatter layout derived from the RUNNER'S OWN sharding
    (the staging views can never drift from what the compiled program
    expects), and the device list per tile."""

    def __init__(self, cfg: StreamConfig, runner) -> None:
        self.runner = runner
        gshape = runner.padded_shape
        if cfg.channels != 1:
            gshape = gshape + (cfg.channels,)
        self.global_shape = gshape
        imap = runner.sharding.devices_indices_map(gshape)
        self.tile_devices = list(runner.mesh.devices.flat)
        specs = []
        for d in self.tile_devices:
            idx = imap[d]
            rows = slice(*idx[0].indices(gshape[0])[:2])
            cols = slice(*idx[1].indices(gshape[1])[:2])
            specs.append((rows, cols))
        self.scatter = frames_io.TileScatter(cfg.frame_shape, specs)
        self.dev_to_tile = {
            d.id: i for i, d in enumerate(self.tile_devices)
        }


def _dispatch(pl, cfg: StreamConfig, pb: _ShardPlumbing) -> None:
    """The main-thread dispatch loop: warm the mesh program on a
    zero-rep launch (the compile overlaps the reader's prefetch — the
    ``prepare_engine`` discipline), then scatter + per-shard H2D +
    launch each staged frame inside the depth-``k`` window."""
    import jax

    runner = pb.runner
    idx, stage = -1, "compute"  # bootstrap failures are compile/compute
    fault_h2d = _faults.site("h2d")
    fault_compute = _faults.site("compute")
    try:
        # Warm-up: the mesh program's compile lands before the first
        # real frame (reps is a traced scalar, so the zero-rep program
        # IS the production program). The zeroed staging tiles are the
        # canvas — nothing extra allocates.
        arrays = [
            jax.device_put(t, d)
            for t, d in zip(pb.scatter.tiles, pb.tile_devices)
        ]
        warm = jax.make_array_from_single_device_arrays(
            pb.global_shape, runner.sharding, arrays
        )
        jax.block_until_ready(runner.run(warm, 0))
        while True:
            item = pl.get(pl.filled_q)
            if item is _EOF:
                break
            idx, bi, crc, wit = item
            stage = "h2d"
            if fault_h2d is not None:
                fault_h2d(idx)
            # The ring slot's H2D-boundary re-verification (the shared
            # single-device discipline), then the per-shard one: each
            # staged tile is CRC'd at scatter and re-verified right
            # before ITS device's upload — ingest integrity per shard.
            _sengine._verify_staged(pl.ring[bi], crc, idx)
            tiles = pb.scatter.scatter(pl.ring[bi])
            pl.free_q.put(bi)  # scatter consumed the ring slot
            tile_crcs = (
                [_checksum.crc32c(t) for t in tiles]
                if cfg.verify_ingest else [None] * len(tiles)
            )
            arrays = []
            for d, (tile, dev) in enumerate(
                    zip(tiles, pb.tile_devices)):
                _sengine._verify_staged(tile, tile_crcs[d], idx)
                with pl.stage("h2d", idx, dev=d) as s:
                    # Fenced per shard: the span holds only THIS
                    # tile's PCIe copy; earlier frames keep computing
                    # on the mesh — the overlap the depth-2 trace
                    # shows (frame i+1 tile uploads inside frame i's
                    # exchange-and-compute).
                    arrays.append(s.fence(jax.device_put(tile, dev)))
            img_dev = jax.make_array_from_single_device_arrays(
                pb.global_shape, runner.sharding, arrays
            )
            stage = "compute"
            if fault_compute is not None:
                fault_compute(idx)
            t_disp = time.perf_counter()
            out = runner.run(img_dev, cfg.repetitions)  # async; donates
            pl.put(pl.inflight_q, (idx, out, t_disp, wit))
        pl.put(pl.inflight_q, _EOF)
    except _sengine._Abort:
        pass
    except BaseException as e:
        pl.fail(stage, max(idx, 0), e)


def _drain(pl, cfg: StreamConfig, pb: _ShardPlumbing) -> None:
    """Fence the mesh compute in dispatch order (watchdogged), copy
    each shard back D2H (split per shard, ``dev=``-tagged spans), crop
    the pad off, free the window slot, hand off to the writer."""
    idx, stage = -1, "compute"
    fault_d2h = _faults.site("d2h")
    fault_corrupt = _faults.site("integrity.corrupt_result")
    timeout_s = _deadline.resolve(cfg.dispatch_timeout_s)
    try:
        while True:
            item = pl.get(pl.inflight_q)
            if item is _EOF:
                pl.put(pl.write_q, _EOF)
                return
            idx, out_dev, t_disp, wit = item
            stage = "compute"
            with pl.stage("compute", idx, t0=t_disp):
                _deadline.fence(out_dev, timeout_s,
                                f"stream.compute[frame={idx},shard]")
            stage = "d2h"
            frame = np.empty(cfg.frame_shape, np.uint8)
            for shard in out_dev.addressable_shards:
                d = pb.dev_to_tile[shard.device.id]
                with pl.stage("d2h", idx, dev=d):
                    if fault_d2h is not None:
                        fault_d2h(idx)
                    piece = np.asarray(shard.data)
                pb.scatter.gather_into(frame, [(d, piece)])
            if fault_corrupt is not None and _checksum.fired(
                    fault_corrupt, idx):
                _checksum.corrupt_array(frame)
            pl.release_window()
            pl.put(pl.write_q, (idx, frame, wit))
    except _sengine._Abort:
        pass
    except BaseException as e:
        pl.fail(stage, max(idx, 0), e)


def run_shard_stream(cfg: StreamConfig, devices,
                     shard: Tuple[int, int], model,
                     source, sink, start_frame: int) -> dict:
    """One sharded-stream pipeline lifetime over the ``shard`` = (R, C)
    mesh (the spatial analog of :func:`tpu_stencil.parallel.fanout
    .run_mesh_frames`). The caller (:func:`tpu_stencil.stream.engine
    ._run_stream_once`) owns source/sink lifecycle, resume resolution
    and result assembly; this returns ``{"frames", "stage_seconds",
    "backend", "schedule", "n_devices"}`` or raises
    :class:`~tpu_stencil.stream.engine.StreamFailure`. The mesh program
    comes from the PROCESS-SHARED runner cache — a geometry serve
    already compiled is a hit here, and vice versa."""
    import threading

    from tpu_stencil.parallel import sharded as _psharded

    r, c = shard
    devices = list(devices)
    if r * c > len(devices):
        raise ValueError(
            f"--shard-frames {r}x{c} asks for {r * c} devices, "
            f"have {len(devices)}"
        )
    runner = _psharded.shared_runner(
        model, (cfg.height, cfg.width), cfg.channels,
        mesh_shape=(r, c), devices=devices, overlap=cfg.overlap,
        registry=obs.registry(),
    )
    if runner is None:
        # Unlike serve there is no bucket path to fall back to mid-
        # stream: an explicitly requested topology the mesh cannot
        # serve fails loudly, naming the constraint.
        raise ValueError(
            f"--shard-frames {r}x{c} cannot serve a "
            f"{cfg.height}x{cfg.width} frame: the per-device tile is "
            f"smaller than the filter halo (or the boundary refuses "
            f"padding); use a smaller mesh or a larger frame"
        )
    pb = _ShardPlumbing(cfg, runner)
    pl = _sengine._Pipeline(cfg)
    done = [start_frame]

    def save_progress(frames_done: int) -> None:
        from tpu_stencil.runtime import checkpoint as ckpt

        ckpt.save_stream_progress(cfg, frames_done, shard_frames=shard)

    threads = [
        threading.Thread(
            target=_sengine._reader, args=(pl, source, start_frame),
            name="shardstream-reader", daemon=True,
        ),
        threading.Thread(
            target=_drain, args=(pl, cfg, pb),
            name="shardstream-drain", daemon=True,
        ),
        threading.Thread(
            target=_sengine._writer, args=(pl, sink, done, save_progress),
            name="shardstream-writer", daemon=True,
        ),
    ]
    try:
        for t in threads:
            t.start()
        _dispatch(pl, cfg, pb)
        # Clean runs end via the sentinel cascade; failed runs via the
        # stop flag. Like the single-device engine, never wait
        # indefinitely on a reader parked in a blocking pipe read.
        for t in threads:
            while t.is_alive() and not pl.stop.is_set():
                t.join(timeout=0.1)
    finally:
        pl.stop.set()
        for t in threads:
            t.join(timeout=1.0)
        pl.zero_gauge()
    if pl.failure is not None:
        stage, frame_index, cause = pl.failure
        raise _sengine.StreamFailure(stage, frame_index, cause) from cause
    return {
        "frames": done[0] - start_frame,
        "stage_seconds": dict(pl.stage_seconds),
        "backend": runner.backend,
        "schedule": runner.schedule,
        "n_devices": r * c,
    }
