"""Utilities: timing, logging."""

from tpu_stencil.utils.timing import Timer, time_compute

__all__ = ["Timer", "time_compute"]
