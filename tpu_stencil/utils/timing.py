"""Timing with the reference's headline-metric semantics.

The reference's MPI metric is: barrier, ``MPI_Wtime`` around the compute/comm
loop only (file I/O excluded), then max across ranks
(``mpi/mpi_convolution.c:151-155,242,264-275``). The TPU-native equivalent:
``jax.block_until_ready`` fences (device queue drained = barrier), a
monotonic clock around the on-device loop only, and a max across host
processes for multi-host runs.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Tuple

import jax


class Timer:
    """Monotonic stopwatch; ``elapsed`` in seconds.

    ``elapsed`` is live: read inside the ``with`` block it returns the time
    accumulated so far (a return statement inside the block sees real time,
    not 0), after exit it is frozen at the block's duration. Read before the
    context is ever entered it raises :class:`RuntimeError` — an un-entered
    timer has no elapsed time, and silently returning 0.0 turned a missing
    ``with`` into a plausible-looking measurement.

    ``label`` names what is being timed (``tpu_stencil.obs`` spans wrap a
    labeled Timer rather than forking the stopwatch); it appears in the
    unentered-read error so the broken call site is findable.
    """

    def __init__(self, label: Optional[str] = None) -> None:
        self.label = label
        self._start: Optional[float] = None
        self._frozen: float = -1.0

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        self._frozen = -1.0  # re-entry restarts the stopwatch
        return self

    def __exit__(self, *exc) -> None:
        self._frozen = time.perf_counter() - self._start

    @property
    def elapsed(self) -> float:
        if self._frozen >= 0.0:
            return self._frozen
        if self._start is not None:
            return time.perf_counter() - self._start
        what = f"Timer({self.label!r})" if self.label else "Timer"
        raise RuntimeError(
            f"{what}.elapsed read before the context was entered; "
            "use 'with Timer() as t: ...' and read t.elapsed inside or after"
        )


def max_across_processes(seconds: float) -> float:
    """Max-reduce a host-side scalar across JAX processes (multi-host); the
    analog of the reference's Send/Recv max at ``mpi/mpi_convolution.c:264-275``.
    Single-process: identity."""
    if jax.process_count() == 1:
        return seconds
    from jax.experimental import multihost_utils

    import numpy as np

    all_times = multihost_utils.process_allgather(np.float32(seconds))
    return float(all_times.max())


def time_compute(fn: Callable[..., Any], *args, **kwargs) -> Tuple[Any, float]:
    """Run ``fn`` with a barrier-equivalent fence before and after; return
    (result, compute-only wall-clock seconds, max across processes)."""
    args = jax.block_until_ready(args)  # drain pending transfers = barrier
    with Timer() as t:
        out = fn(*args, **kwargs)
        out = jax.block_until_ready(out)
    return out, max_across_processes(t.elapsed)
